"""Fleet-scale vectorized RL training (ROADMAP item 1 — dragg_tpu/rl/fleet,
docs/architecture.md §17).

Contracts pinned here:

* C = 1 equivalence: ``run_rl_agg`` with ``fleet.communities = 1`` is
  NUMERICALLY IDENTICAL to the pre-fleet single-community RL run (the
  same pattern as the event-free byte-identity pin in
  tests/test_scenarios.py);
* per-community exploration streams derive from the fleet seed stride
  (``random_seed + c * seed_stride`` — the population's own derivation),
  so a C=2 run's community 0 shares community 0's C=1 seed;
* C >= 8 trains both RL cases on the conftest 8-device CPU mesh under
  ONE compiled pattern set (no per-community recompile);
* scenario event timelines reach the shared policy's observation and
  heterogeneous schedules produce heterogeneous actions;
* the optional "mpc" gradient mode (jvp through the branch-free relaxed
  solve) engages and stays finite.

Heavy legs are slow-marked with light siblings (round-11 budget
convention); the ddpg fleet-core unit tests live in tests/test_rl_neural.py
and the bit-exact fleet resume in tests/test_checkpoint.py.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dragg_tpu.config import default_config
from dragg_tpu.rl.core import RLObservation, params_from_config
from dragg_tpu.rl.fleet import (
    FLEET_SA_DIM,
    FLEET_STATE_DIM,
    FLEET_STATE_SCALARS,
    N_EVENT_FEATURES,
    FleetObservation,
    community_noise_keys,
    community_seeds,
    event_feature_table,
    fleet_linear_step,
    fleet_params_from_config,
    init_fleet_linear,
)


def _cfg(communities=2, stride=5, **sim_over):
    cfg = default_config()
    cfg["community"]["total_number_homes"] = 4
    cfg["community"]["homes_pv"] = 1
    cfg["simulation"]["start_datetime"] = "2015-01-01 00"
    cfg["simulation"]["end_datetime"] = "2015-01-01 04"
    cfg["home"]["hems"]["prediction_horizon"] = 2
    cfg["simulation"]["run_rbo_mpc"] = False
    cfg["fleet"]["communities"] = communities
    cfg["fleet"]["seed_stride"] = stride
    cfg["telemetry"]["enabled"] = False
    cfg["simulation"].update(sim_over)
    return cfg


def _run(cfg, tmp_path, tag, case):
    from dragg_tpu.aggregator import Aggregator

    agg = Aggregator(cfg, data_dir="", outputs_dir=str(tmp_path / tag))
    agg.run()
    with open(os.path.join(agg.run_dir, case, "results.json")) as f:
        return agg, json.load(f)


# ------------------------------------------------------------------ config
def test_fleet_params_validation():
    cfg = default_config()
    fp = fleet_params_from_config(cfg, 4)
    assert fp.policy == "shared" and fp.n_communities == 4
    # learner_batch = 0 resolves to rl.parameters.batch_size.
    assert fp.learner_batch == int(cfg["rl"]["parameters"]["batch_size"])
    cfg["rl"]["fleet"]["learner_batch"] = 64
    assert fleet_params_from_config(cfg, 4).learner_batch == 64
    cfg["rl"]["fleet"]["policy"] = "bogus"
    with pytest.raises(ValueError, match="policy"):
        fleet_params_from_config(cfg, 4)
    cfg["rl"]["fleet"]["policy"] = "per_community"
    cfg["rl"]["fleet"]["gradient"] = "mpc"
    with pytest.raises(ValueError, match="shared"):
        fleet_params_from_config(cfg, 4)


def test_run_shape_carries_rl_fleet_key(tmp_path):
    """The fleet-RL agent-carry layout is a checkpoint-shape dimension:
    a policy-layout flip must invalidate a resume, not crash
    load_pytree's leaf-count check."""
    from dragg_tpu.aggregator import Aggregator

    cfg = _cfg(run_rl_agg=True)
    a = Aggregator(cfg, data_dir="", outputs_dir=str(tmp_path))
    shape = a._run_shape()
    assert shape["rl_fleet"] is not None
    cfg2 = _cfg(run_rl_agg=True)
    cfg2["rl"]["fleet"]["policy"] = "per_community"
    b = Aggregator(cfg2, data_dir="", outputs_dir=str(tmp_path))
    assert b._run_shape()["rl_fleet"] != shape["rl_fleet"]
    # Shape-determining hyperparameters are part of the key too: a DDPG
    # width edit or a tracker-window edit re-sizes carry leaves and must
    # invalidate, not crash load_pytree (review finding, round 15).
    cfg3 = _cfg(run_rl_agg=True)
    cfg3["rl"]["parameters"]["agent"] = "ddpg"
    cfg4 = _cfg(run_rl_agg=True)
    cfg4["rl"]["parameters"]["agent"] = "ddpg"
    cfg4["tpu"]["ddpg_hidden"] = 32
    k3 = Aggregator(cfg3, data_dir="",
                    outputs_dir=str(tmp_path))._run_shape()["rl_fleet"]
    k4 = Aggregator(cfg4, data_dir="",
                    outputs_dir=str(tmp_path))._run_shape()["rl_fleet"]
    assert k3 != k4
    cfg5 = _cfg(run_rl_agg=True)
    cfg5["agg"]["rl"] = {"prev_timesteps": 6}
    k5 = Aggregator(cfg5, data_dir="",
                    outputs_dir=str(tmp_path))._run_shape()["rl_fleet"]
    assert k5 != shape["rl_fleet"]
    # No fleet RL case → the key is inert (None), so baseline fleet
    # checkpoints are untouched by RL config edits.
    c = Aggregator(_cfg(), data_dir="", outputs_dir=str(tmp_path))
    assert c._run_shape()["rl_fleet"] is None


# ------------------------------------------------- seed-stride determinism
def test_community_noise_keys_follow_fleet_seed_stride():
    """Satellite regression: exploration keys derive from the SAME
    ``random_seed + c * seed_stride`` ladder as the population, so a C=2
    run's community 0 matches the corresponding C=1 stream and community
    1 matches a standalone run seeded at base + stride."""
    cfg = _cfg(communities=2, stride=7)
    base = int(cfg["simulation"]["random_seed"])
    np.testing.assert_array_equal(community_seeds(cfg, 2),
                                  [base, base + 7])
    k2 = np.asarray(community_noise_keys(cfg, 2))
    k1 = np.asarray(community_noise_keys(cfg, 1))
    np.testing.assert_array_equal(k2[0], k1[0])
    # Community 1's stream is the standalone stream of seed base+stride.
    cfg_b = _cfg(communities=1)
    cfg_b["simulation"]["random_seed"] = base + 7
    np.testing.assert_array_equal(
        k2[1], np.asarray(community_noise_keys(cfg_b, 1))[0])
    # A different stride yields different non-zero communities.
    cfg_s = _cfg(communities=2, stride=11)
    assert not np.array_equal(
        np.asarray(community_noise_keys(cfg_s, 2))[1], k2[1])


# ------------------------------------------------------- shared linear core
def _fobs(C, fe=0.1, r=-0.5, events=None):
    f = jnp.float32
    rep = lambda v: jnp.full((C,), v, f)
    obs = RLObservation(rep(fe), rep(0.0), rep(0.25), rep(0.0), rep(r))
    ev = (jnp.zeros((C, N_EVENT_FEATURES), f) if events is None
          else jnp.asarray(events, f))
    return FleetObservation(obs=obs, events=ev, drda=jnp.zeros((C,), f))


def test_fleet_linear_step_shapes_and_determinism():
    C = 3
    cfg = _cfg(communities=C)
    params = params_from_config(cfg)
    fparams = fleet_params_from_config(cfg, C)
    c1 = init_fleet_linear(params, fparams, cfg)
    c2 = init_fleet_linear(params, fparams, cfg)
    step = jax.jit(lambda c, o: fleet_linear_step(c, o, params, fparams))
    for k in range(5):
        c1, r1 = step(c1, _fobs(C, fe=0.1 * k))
        c2, r2 = step(c2, _fobs(C, fe=0.1 * k))
    np.testing.assert_array_equal(np.asarray(c1.theta_mu),
                                  np.asarray(c2.theta_mu))
    assert np.asarray(c1.theta_mu).shape == (FLEET_STATE_DIM,)
    assert np.asarray(c1.theta_q).shape == (FLEET_SA_DIM, params.n_q)
    assert np.asarray(c1.state).shape == (C, FLEET_STATE_SCALARS)
    assert np.asarray(r1.action).shape == (C,)
    assert int(c1.t) == 5
    # The shared replay holds C transitions per step, degenerate t=0
    # dropped: after 5 steps, 4*C valid entries, slot-dense.
    assert np.all(np.isfinite(np.asarray(c1.mem_s[:4 * C])))
    for f in r1:
        assert np.all(np.isfinite(np.asarray(f)))
    # Per-community exploration streams DIVERGE (distinct keys): with
    # identical observations the sampled actions still differ.
    acts = np.asarray(c1.next_action)
    assert len(set(np.round(acts, 8).tolist())) == C


def test_event_features_reach_the_policy():
    """Two steps identical except for one community's event features must
    produce different actions for that community only (the features ride
    the basis tail into μ)."""
    C = 2
    cfg = _cfg(communities=C)
    params = params_from_config(cfg)
    fparams = fleet_params_from_config(cfg, C)
    carry = init_fleet_linear(params, fparams, cfg)
    # Give the policy a nonzero weight on the event tail.
    theta = np.zeros(FLEET_STATE_DIM, np.float32)
    theta[-N_EVENT_FEATURES] = 0.01  # price-shock feature weight
    carry = carry._replace(theta_mu=jnp.asarray(theta),
                           t=jnp.asarray(1, jnp.int32))
    step = jax.jit(lambda c, o: fleet_linear_step(c, o, params, fparams))
    ev = np.zeros((C, N_EVENT_FEATURES), np.float32)
    c_a, _ = step(carry, _fobs(C, events=ev))
    ev2 = ev.copy()
    ev2[1, 0] = 2.0  # tariff shock on community 1 only
    c_b, _ = step(carry, _fobs(C, events=ev2))
    a_a, a_b = np.asarray(c_a.next_action), np.asarray(c_b.next_action)
    assert a_a[0] == pytest.approx(a_b[0])   # community 0 unchanged
    assert a_a[1] != pytest.approx(a_b[1])   # community 1 shifted


def test_event_feature_table_matches_timeline():
    from dragg_tpu.scenarios.timeline import empty_timeline

    tl = empty_timeline(2, 12)
    tl.price[1, 4:8] = 0.04
    tl.cap[0, 2:6] = 3.0          # DR cap on community 0
    tl.cap[1, 8:10] = 0.0         # outage on community 1
    tl.relax[0, 2:6] = 1.0
    feats = event_feature_table(tl, start_index=0, num_timesteps=10,
                                window=2, max_rp=0.02)
    assert feats.shape == (10, 2, N_EVENT_FEATURES)
    # t=4, community 1: both window steps shocked → 0.04/0.02 = 2.
    assert feats[4, 1, 0] == pytest.approx(2.0)
    assert feats[4, 0, 0] == pytest.approx(0.0)
    # t=2, community 0: cap active, relax 1.0/2.
    assert feats[2, 0, 1] == pytest.approx(1.0)
    assert feats[2, 0, 3] == pytest.approx(0.5)
    # t=8, community 1: outage (cap == 0) — outage fraction, cap-active 0.
    assert feats[8, 1, 2] == pytest.approx(1.0)
    assert feats[8, 1, 1] == pytest.approx(0.0)
    # Event-free cells are exact zeros.
    assert np.all(feats[0, :, :] == 0.0)


# ----------------------------------------------------------- C=1 equivalence
@pytest.mark.slow  # two full rl_agg runs; the dispatch keeping C=1 on the
                   # single-community path is structural (run_rl_agg) and
                   # unit-covered by test_run_shape_carries_rl_fleet_key
def test_c1_fleet_rl_agg_matches_single_community(tmp_path):
    """Satellite pin: ``run_rl_agg`` with ``fleet.communities = 1`` is
    numerically identical to the config without a fleet block (the
    dispatch keeps C=1 on the unchanged single-community path)."""
    cfg_fleet = _cfg(communities=1, stride=7, run_rl_agg=True)
    cfg_plain = _cfg(communities=1, run_rl_agg=True)
    del cfg_plain["fleet"]
    _a, res_f = _run(cfg_fleet, tmp_path, "fleet1", "rl_agg")
    _b, res_p = _run(cfg_plain, tmp_path, "plain", "rl_agg")
    np.testing.assert_array_equal(res_f["Summary"]["RP"],
                                  res_p["Summary"]["RP"])
    np.testing.assert_array_equal(res_f["Summary"]["p_grid_aggregate"],
                                  res_p["Summary"]["p_grid_aggregate"])
    for h in (k for k in res_p if k != "Summary"):
        for series, vals in res_p[h].items():
            if isinstance(vals, list):
                assert vals == res_f[h][series], (h, series)
    assert "fleet_rl" not in res_f["Summary"]


# --------------------------------------------------------------- end-to-end
@pytest.mark.slow  # full C=8 MPC fleet training run; light siblings:
                   # test_fleet_rl_simplified_c8_and_learning_signal (e2e)
                   # + test_c1_fleet_rl_agg_matches_single_community (rl_agg)
def test_fleet_rl_agg_c8_one_pattern_set(tmp_path):
    """Acceptance: C=8 trains on the 8-device CPU mesh under ONE compiled
    pattern set — bucket patterns scale with TYPES, never with C — and
    the run emits per-community reward prices + telemetry."""
    assert len(jax.devices()) == 8, "conftest pins the 8-device CPU mesh"
    cfg = _cfg(communities=8, run_rl_agg=True)
    agg, res = _run(cfg, tmp_path, "c8", "rl_agg")
    # The 32-home fleet buckets by TYPE (tpu.bucketed auto threshold):
    # one compiled pattern per home type present (base + pv), never per
    # community.
    assert agg.engine.bucketed
    assert len(agg.engine.bucket_info()) == 2
    assert agg.engine.n_communities == 8
    s = res["Summary"]
    assert s["num_homes"] == 32
    assert len(s["RP"]) == agg.num_timesteps
    assert np.all(np.isfinite(s["RP"]))
    fl = s["fleet_rl"]
    assert fl["communities"] == 8 and fl["policy"] == "shared"
    rp_c = np.asarray(fl["RP_by_community"])
    assert rp_c.shape == (8, agg.num_timesteps)
    # Exploration streams are per community: the announced prices are
    # not fleet-identical.
    assert not np.allclose(rp_c[0], rp_c[1])
    # Agent telemetry: fleet-mean series, schema-compatible + the
    # per-community action matrix.
    with open(os.path.join(agg.run_dir, "rl_agg",
                           "utility_agent-results.json")) as f:
        rl = json.load(f)
    assert len(rl["reward"]) == agg.num_timesteps
    assert len(rl["action_by_community"][0]) == 8
    assert rl["parameters"]["fleet"]["communities"] == 8


def test_fleet_rl_simplified_c8_and_learning_signal(tmp_path):
    """C=8 simplified fleet: whole loop on device, per-community
    trajectories diverge (per-community noise), shared θ updates."""
    cfg = _cfg(communities=8, run_rl_simplified=True)
    agg, res = _run(cfg, tmp_path, "simp8", "simplified")
    s = res["Summary"]
    assert len(s["p_grid_aggregate"]) == agg.num_timesteps
    assert np.all(np.isfinite(s["p_grid_aggregate"]))
    rp_c = np.asarray(s["fleet_rl"]["RP_by_community"])
    assert rp_c.shape == (8, agg.num_timesteps)
    assert not np.allclose(rp_c[0], rp_c[1])
    # The shared policy moved off init (the learner engaged).
    theta = np.asarray(agg.agent.carry.theta_mu)
    assert theta.shape == (FLEET_STATE_DIM,)
    assert np.all(np.isfinite(theta))


def test_fleet_rl_per_community_mode():
    """per_community policy: C independent reference cores vmapped —
    distinct per-community θ, seeded by the fleet seed ladder (unit leg;
    the aggregator dispatch is covered by the shared-mode e2e tests)."""
    from dragg_tpu.rl.basis import STATE_DIM as SD
    from dragg_tpu.rl.fleet import FleetAgent

    cfg = _cfg(communities=2)
    cfg["rl"]["fleet"]["policy"] = "per_community"
    agent = FleetAgent(cfg, 2)
    assert agent.fparams.policy == "per_community"
    carry = agent.carry
    assert np.asarray(carry.theta_mu).shape == (2, SD)
    # Distinct seeds → distinct critic inits.
    assert not np.allclose(np.asarray(carry.theta_q)[0],
                           np.asarray(carry.theta_q)[1])
    step = jax.jit(agent.scan_step)
    for k in range(3):
        carry, rec = step(carry, _fobs(2, fe=0.1 * k))
    assert np.asarray(rec.action).shape == (2,)
    assert int(np.asarray(carry.t)[0]) == 3
    # Independent exploration diverges the community policies.
    assert not np.allclose(np.asarray(carry.next_action)[0],
                           np.asarray(carry.next_action)[1])
    for f in rec:
        assert np.all(np.isfinite(np.asarray(f)))


def test_mpc_gradient_term_changes_policy():
    """Unit pin of the deterministic actor term: a nonzero drda channel
    must move the shared θ_μ under gradient="mpc" and be a no-op under
    "score" — the mechanism itself, without an env in the loop."""
    C = 2
    cfg = _cfg(communities=C)
    params = params_from_config(cfg)
    fobs0 = _fobs(C)
    fobs_g = fobs0._replace(drda=jnp.full((C,), 0.5, jnp.float32))
    outs = {}
    for grad in ("score", "mpc"):
        cfg["rl"]["fleet"]["gradient"] = grad
        fparams = fleet_params_from_config(cfg, C)
        carry = init_fleet_linear(params, fparams, cfg)
        # Step past t=0 so the policy update is live, then one step with
        # the gradient channel populated.
        carry, _ = fleet_linear_step(carry, fobs0, params, fparams)
        c_a, _ = fleet_linear_step(carry, fobs_g, params, fparams)
        c_b, _ = fleet_linear_step(carry, fobs0, params, fparams)
        outs[grad] = (np.asarray(c_a.theta_mu), np.asarray(c_b.theta_mu))
    a, b = outs["mpc"]
    assert not np.allclose(a, b)      # mpc: drda moves the policy
    a, b = outs["score"]
    np.testing.assert_array_equal(a, b)  # score: drda is inert


@pytest.mark.slow  # two simplified fleet training runs; light sibling:
                   # test_mpc_gradient_term_changes_policy (the mechanism)
def test_mpc_gradient_mode_engages(tmp_path):
    """gradient="mpc" (exact response derivative in the simplified case)
    must CHANGE the learned policy vs "score" at identical seeds/config,
    and stay finite — the deterministic actor term is live, not a
    silent no-op."""
    outs = {}
    for grad in ("score", "mpc"):
        cfg = _cfg(communities=2, run_rl_simplified=True)
        cfg["rl"]["fleet"]["gradient"] = grad
        agg, _res = _run(cfg, tmp_path, f"grad_{grad}", "simplified")
        outs[grad] = np.asarray(agg.agent.carry.theta_mu)
        assert np.all(np.isfinite(outs[grad]))
    assert not np.allclose(outs["score"], outs["mpc"])


@pytest.mark.slow  # jvp through the full relaxed MPC solve; light sibling:
                   # test_mpc_gradient_mode_engages (exact linear response)
def test_mpc_gradient_through_relaxed_solve(tmp_path):
    """The rl_agg mpc path: one forward-mode jvp through the reluqp
    family's branch-free relaxed solve per step — runs end-to-end and
    produces finite prices + a policy distinct from score mode."""
    outs = {}
    for grad in ("score", "mpc"):
        cfg = _cfg(communities=2, run_rl_agg=True)
        cfg["home"]["hems"]["solver"] = "reluqp"
        cfg["rl"]["fleet"]["gradient"] = grad
        agg, res = _run(cfg, tmp_path, f"agg_grad_{grad}", "rl_agg")
        assert np.all(np.isfinite(res["Summary"]["RP"]))
        outs[grad] = np.asarray(agg.agent.carry.theta_mu)
    assert not np.allclose(outs["score"], outs["mpc"])


@pytest.mark.slow  # separate engine compile; light siblings:
                   # test_event_features_reach_the_policy + the table unit
def test_fleet_rl_agg_event_timeline_heterogeneous(tmp_path):
    """A tariff shock scheduled on ONE community reaches the shared
    policy's observation (round-13 timeline → event features) and the
    engine's per-community prices — heterogeneous schedules under one
    compiled pattern set."""
    cfg = _cfg(communities=2, run_rl_agg=True)
    cfg["tpu"]["fix_tou_peak"] = True
    cfg["scenarios"]["events"] = [dict(
        kind="tariff_shock", start_hour=1, duration_hours=3,
        price_delta=0.05, communities=[1])]
    agg, res = _run(cfg, tmp_path, "evt", "rl_agg")
    s = res["Summary"]
    assert np.all(np.isfinite(s["RP"]))
    rp_c = np.asarray(s["fleet_rl"]["RP_by_community"])
    assert not np.allclose(rp_c[0], rp_c[1])


def test_fleet_agent_carry_checkpoint_roundtrip(tmp_path):
    """The batched agent carries (shared linear θ/replay/keys and the
    DDPG nested Flax/Adam pytrees) survive the structure-agnostic pytree
    checkpoint — the light sibling of the aggregator-level resume legs
    below / in tests/test_checkpoint.py."""
    from dragg_tpu.checkpoint import load_pytree, save_pytree
    from dragg_tpu.rl import neural
    from dragg_tpu.rl.fleet import init_fleet_ddpg

    C = 2
    cfg = _cfg(communities=C)
    params = params_from_config(cfg)
    fparams = fleet_params_from_config(cfg, C)
    lin = init_fleet_linear(params, fparams, cfg)
    cfg_d = _cfg(communities=C)
    cfg_d["rl"]["parameters"]["agent"] = "ddpg"
    ddpg = init_fleet_ddpg(neural.params_from_config(cfg_d),
                           fleet_params_from_config(cfg_d, C), cfg_d)
    for name, carry in (("linear", lin), ("ddpg", ddpg)):
        path = os.path.join(str(tmp_path), f"{name}.npz")
        save_pytree(path, carry)
        # The template only supplies structure/shapes — the carry itself
        # serves (load_pytree validates leaf count + shapes against it).
        restored = load_pytree(path, carry)
        for a, b in zip(jax.tree.leaves(carry), jax.tree.leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow  # two aggregator runs; light siblings:
                   # test_fleet_agent_carry_checkpoint_roundtrip (carry) +
                   # tests/test_checkpoint.py bit-exact fleet resume (full)
def test_fleet_rl_checkpoint_stop_resume_light(tmp_path):
    """A fleet RL run stopped at its first checkpoint resumes from it
    and completes (the bit-exact 3-run leg lives in
    tests/test_checkpoint.py ``test_fleet_rl_agg_resume_bit_exact``)."""
    from dragg_tpu.aggregator import Aggregator

    cfg = _cfg(communities=2, run_rl_agg=True,
               end_datetime="2015-01-01 03", resume=True,
               checkpoint_interval="hourly")
    out = str(tmp_path / "resumed")
    part = Aggregator(cfg, data_dir="", outputs_dir=out)
    part.stop_after_chunks = 1
    part.run()
    assert part.timestep == 1 and part.timestep < part.num_timesteps
    res = Aggregator(_cfg(communities=2, run_rl_agg=True,
                          end_datetime="2015-01-01 03", resume=True,
                          checkpoint_interval="hourly"),
                     data_dir="", outputs_dir=out)
    res.run()
    assert res.resumed_from is not None
    assert res.timestep == res.num_timesteps
    with open(os.path.join(res.run_dir, "rl_agg", "results.json")) as f:
        s = json.load(f)["Summary"]
    assert len(s["RP"]) == res.num_timesteps
    assert np.asarray(s["fleet_rl"]["RP_by_community"]).shape == \
        (2, res.num_timesteps)
