"""Serving-daemon chaos with REAL engine workers (slow tier).

The fast stub tests (tests/test_serve.py) prove the parent-side
machinery; this file proves the one invariant that needs a real jax
child: the persistent compile cache survives worker death, so a
CHILD_CRASH costs a relaunch, never a recompile (ISSUE 7 satellite —
pinned via the compile_obs cache hit/miss telemetry riding the worker's
staged compile).  The full scenario matrix runs in tools/serve_soak.py.
"""

from __future__ import annotations

import json
import os


import pytest

from dragg_tpu import telemetry
from dragg_tpu.config import default_config
from dragg_tpu.resilience import faults
from dragg_tpu.serve.daemon import ServeDaemon

from tests.test_serve import _get, _post, _wait_terminal

pytestmark = pytest.mark.slow


def test_compile_cache_survives_child_crash(tmp_path, monkeypatch):
    """Kill a real worker after its first executed batch; the replacement
    must reuse the persistent compile cache (compile.done telemetry:
    anything but "miss") and warm up no slower than the cold start."""
    cfg = default_config()
    cfg["community"]["total_number_homes"] = 4
    cfg["community"]["homes_pv"] = 1
    cfg["community"]["homes_battery"] = 0
    cfg["community"]["homes_pv_battery"] = 0
    cfg["home"]["hems"]["prediction_horizon"] = 2
    # Hermetic cache: cold by construction for gen 1, shared for gen 2.
    cfg["tpu"]["compile_cache_dir"] = str(tmp_path / "cache")
    cfg["serve"].update({"port": 0, "poll_s": 0.02, "backoff_s": 0.1,
                         "request_retries": 3, "batch_deadline_s": 300.0,
                         "worker_stall_s": 300.0, "drain_s": 30.0})
    monkeypatch.setenv("DRAGG_FAULT_STATE", str(tmp_path / "fault_state"))
    os.makedirs(tmp_path / "fault_state", exist_ok=True)
    monkeypatch.setenv(faults.ENV, "sigkill@serve_batch:2:once")
    faults.reset_plan()

    daemon = ServeDaemon(cfg, str(tmp_path / "serve"), platform="cpu")
    daemon.start()
    try:
        base = f"http://127.0.0.1:{daemon.port}"
        # Two timesteps → two batches; the sigkill fires at batch 2.
        ids = ["k0", "k1"]
        for i, rid in enumerate(ids):
            assert _post(base, {"id": rid, "t": i, "home": i})[0] == 202
        outcomes = _wait_terminal(base, ids, timeout_s=600)
        assert all(o["status"] == "done" for o in outcomes.values())
        assert daemon.slots[0].gen >= 2, "worker was never relaunched"
    finally:
        events_path = os.path.join(daemon.serve_dir, telemetry.EVENTS_FILE)
        daemon.stop(drain=True)
    faults.reset_plan()

    events = telemetry.tail_events(events_path, limit=100000,
                                   tail_bytes=1 << 26)
    exits = [e for e in events if e.get("event") == "serve.worker.exit"]
    assert any(e.get("failure") == "CHILD_CRASH" for e in exits), exits
    compiles = [e for e in events if e.get("event") == "compile.done"]
    assert len(compiles) >= 2, \
        f"expected one staged compile per worker generation: {compiles}"
    # Generation 1 populated the cold cache; the replacement must NOT
    # recompile.  ("unknown" = the warm compile beat the persistence
    # floor — also not a recompile; only "miss" is the regression.)
    assert compiles[-1].get("cache") != "miss", compiles
    readies = [e for e in events if e.get("event") == "serve.worker.ready"]
    assert len(readies) >= 2
    cold, warm = readies[0], readies[-1]
    assert warm["warmup_s"] < cold["warmup_s"], \
        f"warm restart {warm['warmup_s']}s did not beat cold " \
        f"{cold['warmup_s']}s"
    # Exactly-once delivery held across the kill -9.
    recs = [json.loads(line) for line in
            open(os.path.join(daemon.serve_dir, "journal.jsonl"))]
    done = [r["id"] for r in recs if r["state"] == "done"]
    assert sorted(done) == ids


def test_real_engine_serves_state_override(tmp_path):
    """One real request end-to-end: the response is a finite MPC action
    and the state override actually reached the engine (a colder home
    answers with its overridden temperature trajectory, not the
    template's)."""
    cfg = default_config()
    cfg["community"]["total_number_homes"] = 4
    cfg["community"]["homes_pv"] = 1
    cfg["community"]["homes_battery"] = 0
    cfg["community"]["homes_pv_battery"] = 0
    cfg["home"]["hems"]["prediction_horizon"] = 2
    cfg["tpu"]["compile_cache_dir"] = str(tmp_path / "cache")
    cfg["serve"].update({"port": 0, "poll_s": 0.02, "drain_s": 30.0,
                         "batch_deadline_s": 300.0,
                         "worker_stall_s": 300.0})
    daemon = ServeDaemon(cfg, str(tmp_path / "serve"), platform="cpu")
    daemon.start()
    try:
        base = f"http://127.0.0.1:{daemon.port}"
        assert _post(base, {"id": "warm", "t": 0, "home": 0})[0] == 202
        assert _post(base, {"id": "cold", "t": 1, "home": 0,
                            "state": {"temp_in": 10.0}})[0] == 202
        outcomes = _wait_terminal(base, ["warm", "cold"], timeout_s=600)
        warm = outcomes["warm"]["response"]
        cold = outcomes["cold"]["response"]
        for resp in (warm, cold):
            assert resp["platform"] == "cpu"
            assert all(isinstance(resp[k], float) for k in
                       ("p_grid", "temp_in", "cost"))
        # A 10 °C start must leave the one-step indoor temperature far
        # below the ~20 °C template trajectory regardless of duty choice.
        assert cold["temp_in"] < warm["temp_in"] - 5.0
        code, body = _get(base, "/readyz")
        assert code == 200 and body["ready"]
    finally:
        daemon.stop(drain=True)
