"""MODEL parity: the canonicalized QP vs an independent transcription of
the reference's cvxpy program (round 5 — VERDICT r4 missing #2).

tests/test_qp_parity.py proves our SOLVERS find the optimum of OUR
matrices; this file proves the matrices encode the REFERENCE'S MODEL.
The `_reference_program` builder below transcribes the reference's
constraint equations directly from dragg/mpc_calc.py — variable by
variable, never touching ops/qp.py's assembly — and both programs are
solved with the same trusted HiGHS backend on the same seeded inputs
(shared fixture recipe, dragg_tpu/fixtures.py).  If the two optima
disagree, the canonicalization dropped or distorted part of the model.

Transcribed semantics (file:line cites into the reference):
* indoor-air EV dynamics + bands  — dragg/mpc_calc.py:312-319
* applied (k=1) indoor temp on the TRUE OAT — :321-327
* water-heater EV dynamics with draw mixing — :330-336
* applied WH temp (NO draw mixing on this row) — :338-342
* p_load / duty bounds / season gate — :296-307,344-350
* battery storage dynamics + caps — :359-372
* PV with curtailment — :378-384
* p_grid by home type — :386-432
* discounted linear cost objective — :437-446
* integer duty counts (GLPK_MI) — :171-173
"""

import numpy as np
import pytest
from scipy.optimize import Bounds, LinearConstraint, linprog, milp

from dragg_tpu.fixtures import assemble_community_qp
from dragg_tpu.ops.qp import densify_A

TAP = 15.0  # assumed cold tap temperature (dragg/mpc_calc.py:183)


def _reference_program(i, inp):
    """Build home ``i``'s program straight from the reference equations.

    Returns (c, c0, A_eq, b_eq, lb, ub, idx) with variable layout
    cool(H) heat(H) wh(H) tin_ev(H+1) twh_ev(H+1) tin1 twh1
    [pch(H) pd(H) e(H+1)] [curt(H)] — c0 is the constant objective term
    (uncurtailed PV credit).
    """
    b = inp["batch"]
    H = inp["price"].shape[1]
    dt, s = inp["dt"], inp["s"]
    r, C = float(b.hvac_r[i]), float(b.hvac_c[i])
    pc, ph = float(b.hvac_p_c[i]), float(b.hvac_p_h[i])
    whr, whc, whp = float(b.wh_r[i]), float(b.wh_c[i]), float(b.wh_p[i])
    tank = float(inp["tank"][i])
    draw = inp["draw_size"][i]
    dfr = draw / tank
    rem = 1.0 - dfr
    oat = inp["oat_window"].astype(np.float64)
    ghi = inp["ghi_window"].astype(np.float64)
    price = inp["price"][i].astype(np.float64)
    w = inp["discount"] ** np.arange(H)
    has_pv = bool(b.has_pv[i])
    has_batt = bool(b.has_batt[i])

    n = 3 * H + 2 * (H + 1) + 2
    o_cool, o_heat, o_wh = 0, H, 2 * H
    o_tin, o_twh = 3 * H, 4 * H + 1
    o_tin1, o_twh1 = 5 * H + 2, 5 * H + 3
    o_pch = o_pd = o_e = o_curt = None
    if has_batt:
        o_pch, o_pd, o_e = n, n + H, n + 2 * H
        n += 3 * H + 1
    if has_pv:
        o_curt = n
        n += H

    a_in = 3600.0 / (r * C * dt)       # K per K of (OAT - Tin)
    g_c = 3600.0 * pc / (C * dt)       # K per cool count
    g_h = 3600.0 * ph / (C * dt)
    a_wh = 3600.0 / (whr * whc * dt)
    g_w = 3600.0 * whp / (whc * dt)

    rows, rhs = [], []

    def eq(coeffs, rh):
        row = np.zeros(n)
        for j, v in coeffs:
            row[j] += v
        rows.append(row)
        rhs.append(rh)

    # tin_ev[0] pin (mpc_calc.py:313)
    eq([(o_tin, 1.0)], inp["temp_in_init"][i])
    # tin_ev dynamics (mpc_calc.py:314-317): tin[k+1] = tin[k](1-a_in)
    # + a_in*oat[k+1] - g_c*cool[k] + g_h*heat[k]
    for k in range(H):
        eq([(o_tin + k + 1, 1.0), (o_tin + k, -(1.0 - a_in)),
            (o_cool + k, g_c), (o_heat + k, -g_h)], a_in * oat[k + 1])
    # applied temp on the TRUE oat[1] (mpc_calc.py:321-324)
    eq([(o_tin1, 1.0), (o_cool, g_c), (o_heat, -g_h)],
       (1.0 - a_in) * inp["temp_in_init"][i] + a_in * oat[1])
    # twh_ev[0] pin (draw-mixed init comes in via inp; mpc_calc.py:330)
    eq([(o_twh, 1.0)], inp["temp_wh_init"][i])
    # twh_ev dynamics with draw mixing (mpc_calc.py:331-333):
    # twh[k+1] = mix*(1-a_wh) + a_wh*tin[k+1] + g_w*wh[k],
    # mix = rem[k+1]*twh[k] + dfr[k+1]*TAP
    for k in range(H):
        eq([(o_twh + k + 1, 1.0),
            (o_twh + k, -rem[k + 1] * (1.0 - a_wh)),
            (o_tin + k + 1, -a_wh), (o_wh + k, -g_w)],
           dfr[k + 1] * TAP * (1.0 - a_wh))
    # applied WH temp — NO mixing on this row (mpc_calc.py:338-340)
    eq([(o_twh1, 1.0), (o_tin + 1, -a_wh), (o_wh, -g_w)],
       (1.0 - a_wh) * inp["temp_wh_init"][i])
    if has_batt:
        ce, de = float(b.batt_ch_eff[i]), float(b.batt_disch_eff[i])
        eq([(o_e, 1.0)], inp["e_batt_init"][i])   # mpc_calc.py:363
        for k in range(H):                         # mpc_calc.py:360-362
            eq([(o_e + k + 1, 1.0), (o_e + k, -1.0),
                (o_pch + k, -ce / dt), (o_pd + k, -1.0 / (de * dt))], 0.0)

    lb = np.full(n, -np.inf)
    ub = np.full(n, np.inf)
    lb[o_cool:o_cool + H] = 0.0
    ub[o_cool:o_cool + H] = inp["cool_cap"][i]     # season gate :302-307
    lb[o_heat:o_heat + H] = 0.0
    ub[o_heat:o_heat + H] = inp["heat_cap"][i]
    lb[o_wh:o_wh + H] = 0.0
    ub[o_wh:o_wh + H] = s                          # :300-301
    # tin_ev[1:] banded; index 0 pinned by equality (:318-319)
    lb[o_tin + 1:o_tin + H + 1] = float(b.temp_in_min[i])
    ub[o_tin + 1:o_tin + H + 1] = float(b.temp_in_max[i])
    lb[o_tin1], ub[o_tin1] = float(b.temp_in_min[i]), float(b.temp_in_max[i])
    # twh_ev band INCLUDES index 0 (:334-335 — "self.temp_wh_ev >= ...")
    lb[o_twh:o_twh + H + 1] = float(b.temp_wh_min[i])
    ub[o_twh:o_twh + H + 1] = float(b.temp_wh_max[i])
    lb[o_twh1], ub[o_twh1] = float(b.temp_wh_min[i]), float(b.temp_wh_max[i])
    if has_batt:
        mr = float(b.batt_max_rate[i])
        lb[o_pch:o_pch + H], ub[o_pch:o_pch + H] = 0.0, mr      # :364-365
        lb[o_pd:o_pd + H], ub[o_pd:o_pd + H] = -mr, 0.0         # :366-367
        lb[o_e + 1:o_e + H + 1] = float(b.batt_cap_min[i])      # :368-369
        ub[o_e + 1:o_e + H + 1] = float(b.batt_cap_max[i])
    if has_pv:
        lb[o_curt:o_curt + H], ub[o_curt:o_curt + H] = 0.0, 1.0  # :382-383

    # Objective: sum_k w_k price_k p_grid_k (mpc_calc.py:441-446), p_grid
    # per home type (:386-432); PV term p_pv = area*eff*ghi*(1-curt)/1000.
    c = np.zeros(n)
    c0 = 0.0
    wp = w * price
    c[o_cool:o_cool + H] = wp * s * pc
    c[o_heat:o_heat + H] = wp * s * ph
    c[o_wh:o_wh + H] = wp * s * whp
    if has_batt:
        c[o_pch:o_pch + H] = wp * s
        c[o_pd:o_pd + H] = wp * s
    if has_pv:
        pvc = float(b.pv_area[i]) * float(b.pv_eff[i]) * ghi[:H] / 1000.0
        c[o_curt:o_curt + H] = wp * s * pvc
        c0 = -float(np.sum(wp * s * pvc))

    idx = dict(cool=o_cool, heat=o_heat, wh=o_wh, pch=o_pch, pd=o_pd,
               curt=o_curt, n=n, H=H)
    return c, c0, np.array(rows), np.array(rhs), lb, ub, idx


def _solve_ref(c, A, beq, lb, ub, integrality=None):
    if integrality is None:
        res = linprog(c, A_eq=A, b_eq=beq,
                      bounds=list(zip(np.where(np.isfinite(lb), lb, -np.inf),
                                      np.where(np.isfinite(ub), ub, np.inf))),
                      method="highs")
        return (res.fun, res.x) if res.success else (None, None)
    res = milp(c=c, constraints=LinearConstraint(A, beq, beq),
               bounds=Bounds(lb, ub), integrality=integrality)
    return (res.fun, res.x) if res.status == 0 else (None, None)


def _our_objective_in_ref_units(x, lay, i, inp):
    """Evaluate the REFERENCE objective on OUR optimal point: recover the
    duties/battery/curtailment columns and apply the reference cost
    formula — catches objective-scaling drift that comparing raw q@x
    cannot."""
    b = inp["batch"]
    H = inp["price"].shape[1]
    s = inp["s"]
    w = inp["discount"] ** np.arange(H)
    wp = w * inp["price"][i].astype(np.float64)
    cool = x[lay.i_cool:lay.i_cool + H]
    heat = x[lay.i_heat:lay.i_heat + H]
    wh = x[lay.i_wh:lay.i_wh + H]
    p_load = s * (float(b.hvac_p_c[i]) * cool + float(b.hvac_p_h[i]) * heat
                  + float(b.wh_p[i]) * wh)
    p_grid = p_load.copy()
    if b.has_batt[i]:
        p_grid += s * (x[lay.i_pch:lay.i_pch + H] + x[lay.i_pd:lay.i_pd + H])
    if b.has_pv[i]:
        pvc = (float(b.pv_area[i]) * float(b.pv_eff[i])
               * inp["ghi_window"][:H].astype(np.float64) / 1000.0)
        p_grid -= s * pvc * (1.0 - x[lay.i_curt:lay.i_curt + H])
    return float(np.sum(wp * p_grid))


@pytest.mark.parametrize("horizon_hours", [4, 8])
def test_canonicalized_qp_encodes_reference_model(horizon_hours):
    """Home by home: HiGHS optimum of OUR matrices == HiGHS optimum of the
    independently transcribed reference program, both as the LP relaxation
    and as the full MILP (integer duty counts)."""
    qp, pat, lay, s, inp = assemble_community_qp(
        horizon_hours=horizon_hours, n_homes=6, season="heat",
        return_inputs=True)
    A = np.asarray(densify_A(pat, qp.vals), np.float64)
    beq = np.asarray(qp.b_eq, np.float64)
    l = np.asarray(qp.l_box, np.float64)
    u = np.asarray(qp.u_box, np.float64)
    q = np.asarray(qp.q, np.float64)
    H = lay.H

    our_int = np.zeros(pat.n)
    our_int[lay.i_cool:lay.i_cool + H] = 1
    our_int[lay.i_heat:lay.i_heat + H] = 1
    our_int[lay.i_wh:lay.i_wh + H] = 1

    n_checked = 0
    for i in range(A.shape[0]):
        c, c0, Ar, br, lb, ub, idx = _reference_program(i, inp)
        ref_int = np.zeros(idx["n"])
        for key in ("cool", "heat", "wh"):
            ref_int[idx[key]:idx[key] + H] = 1

        for integer in (False, True):
            ref_obj, _ = _solve_ref(c, Ar, br, lb, ub,
                                    ref_int if integer else None)
            ours_obj, ours_x = _solve_ref(
                q[i], A[i], beq[i],
                np.where(np.isfinite(l[i]), l[i], -np.inf),
                np.where(np.isfinite(u[i]), u[i], np.inf),
                our_int if integer else None)
            if ref_obj is None or ours_obj is None:
                # Feasibility must agree between the two programs.
                assert ref_obj is None and ours_obj is None, (
                    f"home {i} H={horizon_hours} int={integer}: one model "
                    f"feasible, the other not")
                continue
            ref_total = ref_obj + c0
            ours_total = _our_objective_in_ref_units(ours_x, lay, i, inp)
            scale = max(abs(ref_total), 1e-3)
            gap = abs(ours_total - ref_total) / scale
            assert gap < 2e-3, (
                f"home {i} H={horizon_hours} int={integer}: our optimum "
                f"{ours_total:.6f} vs reference-model optimum "
                f"{ref_total:.6f} (gap {gap:.2e}) — canonicalization "
                f"drift")
            n_checked += 1
    assert n_checked >= 8
