"""ADMM kernel tests vs scipy (SURVEY.md §4(b): QP-solver kernel tests
against a CPU reference on identical matrices, <=1% objective-cost gap)."""

import numpy as np
import pytest
import scipy.optimize

import jax.numpy as jnp

from dragg_tpu.ops.admm import admm_solve


def random_feasible_lp(rng, n=12, m_eq=5):
    """Random equality-constrained box LP guaranteed feasible."""
    A = rng.randn(m_eq, n)
    x_feas = rng.uniform(0.2, 0.8, n)
    b = A @ x_feas
    l = np.zeros(n)
    u = np.ones(n)
    q = rng.randn(n)
    return A, b, l, u, q


def scipy_lp(A, b, l, u, q):
    res = scipy.optimize.linprog(
        q, A_eq=A, b_eq=b, bounds=list(zip(l, u)), method="highs"
    )
    return res


class TestADMMvsScipy:
    def test_batch_of_random_lps(self, rng):
        B, n, m_eq = 16, 12, 5
        As, bs, ls, us, qs, refs = [], [], [], [], [], []
        for _ in range(B):
            A, b, l, u, q = random_feasible_lp(rng, n, m_eq)
            res = scipy_lp(A, b, l, u, q)
            assert res.success
            As.append(A); bs.append(b); ls.append(l); us.append(u); qs.append(q)
            refs.append(res.fun)
        # fp32 ADMM floors around 1e-4 residuals on LPs (no polish step);
        # the acceptance criterion is the north-star <=1% objective gap.
        sol = admm_solve(
            jnp.asarray(np.stack(As), dtype=jnp.float32),
            jnp.asarray(np.stack(bs), dtype=jnp.float32),
            jnp.asarray(np.stack(ls), dtype=jnp.float32),
            jnp.asarray(np.stack(us), dtype=jnp.float32),
            jnp.asarray(np.stack(qs), dtype=jnp.float32),
            # Kernel-level check on synthetic LPs: pin reg to the
            # near-exact setting (the package default 1e-3 is tuned to the
            # MPC problems' scaling and can bias arbitrary LPs past 1%).
            iters=2000, eps_abs=2e-3, eps_rel=2e-3, reg=1e-6,
        )
        assert bool(np.all(np.asarray(sol.solved))), (
            f"unsolved: r_prim={np.asarray(sol.r_prim)}, r_dual={np.asarray(sol.r_dual)}"
        )
        obj = np.einsum("bn,bn->b", np.asarray(sol.x), np.stack(qs))
        ref = np.array(refs)
        scale = np.maximum(np.abs(ref), 1e-3)
        gap = np.abs(obj - ref) / scale
        assert np.max(gap) < 0.01, f"objective gap {gap}"

    def test_infinite_bounds(self, rng):
        """Free variables (inf bounds) must work — the QP template uses them
        for equality-pinned states."""
        n, m_eq = 6, 2
        A = rng.randn(m_eq, n)
        x_feas = rng.uniform(-1, 1, n)
        b = A @ x_feas
        l = np.full(n, -np.inf); l[:3] = -1.0
        u = np.full(n, np.inf); u[:3] = 1.0
        q = np.abs(rng.randn(n)) + 0.1
        # Make it bounded: add box on the free vars via A rows? Instead make
        # q push toward the box vars only and pin the frees by equality.
        A2 = np.vstack([A, np.eye(n)[3:]])
        b2 = np.concatenate([b, x_feas[3:]])
        res = scipy_lp(A2, b2, l, u, q)
        assert res.success
        sol = admm_solve(
            jnp.asarray(A2[None], dtype=jnp.float32),
            jnp.asarray(b2[None], dtype=jnp.float32),
            jnp.asarray(l[None], dtype=jnp.float32),
            jnp.asarray(u[None], dtype=jnp.float32),
            jnp.asarray(q[None], dtype=jnp.float32),
            # Kernel-level check on synthetic LPs: pin reg to the
            # near-exact setting (the package default 1e-3 is tuned to the
            # MPC problems' scaling and can bias arbitrary LPs past 1%).
            iters=2000, eps_abs=2e-3, eps_rel=2e-3, reg=1e-6,
        )
        assert bool(sol.solved[0])
        obj = float(np.asarray(sol.x)[0] @ q)
        assert abs(obj - res.fun) / max(abs(res.fun), 1e-3) < 0.01

    def test_infeasible_flags_unsolved(self, rng):
        """Contradictory equalities must come back unsolved, not silently
        'solved' — this is what routes homes to the fallback controller."""
        n = 4
        A = np.vstack([np.eye(n)[:1], np.eye(n)[:1]])
        b = np.array([0.2, 0.8])  # x0 = 0.2 and x0 = 0.8
        l, u = np.zeros(n), np.ones(n)
        q = np.ones(n)
        sol = admm_solve(
            jnp.asarray(A[None], dtype=jnp.float32),
            jnp.asarray(b[None], dtype=jnp.float32),
            jnp.asarray(l[None], dtype=jnp.float32),
            jnp.asarray(u[None], dtype=jnp.float32),
            jnp.asarray(q[None], dtype=jnp.float32),
            iters=500,
        )
        assert not bool(sol.solved[0])

    def test_warm_start_reduces_iters(self, rng):
        A, b, l, u, q = random_feasible_lp(rng, 12, 5)
        args = [
            jnp.asarray(v[None], dtype=jnp.float32) for v in (A, b, l, u, q)
        ]
        cold = admm_solve(*args, iters=4000, eps_abs=1e-4, eps_rel=1e-4, check_every=10)
        warm = admm_solve(
            *args, iters=4000, eps_abs=1e-4, eps_rel=1e-4, check_every=10,
            x0=cold.x, y_box0=cold.y_box, rho0=cold.rho,
        )
        assert int(warm.iters) <= int(cold.iters)


@pytest.mark.slow  # round-11 tier-1 budget trim: opt-in knob measured unhelpful (perf_notes round 5) — not on any default path
def test_anderson_acceleration_solves():
    """The opt-in Anderson path (anderson>0) must keep solutions valid on the
    real community QP: same homes solved, same objectives to tolerance."""
    from test_qp_parity import _assemble_real_step

    from dragg_tpu.ops.admm import admm_solve_qp

    qp, pat = _assemble_real_step(horizon_hours=8, n_homes=6)
    plain = admm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                          iters=2000, anderson=0)
    accel = admm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                          iters=2000, anderson=5)
    np.testing.assert_array_equal(np.asarray(plain.solved), np.asarray(accel.solved))
    q = np.asarray(qp.q)
    obj_p = np.einsum("bn,bn->b", q, np.asarray(plain.x))
    obj_a = np.einsum("bn,bn->b", q, np.asarray(accel.x))
    sel = np.asarray(plain.solved)
    assert sel.sum() >= 4
    np.testing.assert_allclose(obj_a[sel], obj_p[sel], rtol=1e-2, atol=1e-2)
