"""Observability parity (round-1 verdict, missing #5): per-home failure
logs, the VERBOSE solver telemetry toggle, and reset_seed."""

import os

import numpy as np

import pytest

from dragg_tpu.aggregator import Aggregator
from dragg_tpu.config import default_config


def _tiny_cfg():
    cfg = default_config()
    cfg["community"]["total_number_homes"] = 3
    cfg["community"]["homes_pv"] = 0
    cfg["community"]["homes_battery"] = 0
    cfg["community"]["homes_pv_battery"] = 0
    cfg["simulation"]["end_datetime"] = "2015-01-01 06"
    cfg["home"]["hems"]["prediction_horizon"] = 2
    cfg["tpu"]["admm_iters"] = 200
    return cfg


def test_home_failure_logs(tmp_path):
    """Homes flagged unsolved get appended WARN lines in
    home_logs/<name>.log (dragg/mpc_calc.py:655-658 analog)."""
    cfg = _tiny_cfg()
    agg = Aggregator(cfg, data_dir=None, outputs_dir=str(tmp_path / "out"))
    agg.get_homes()
    agg.set_run_dir()
    agg.timestep = 5
    mask = np.ones((2, 3))
    mask[0, 1] = 0.0  # home 1 fails at chunk step 0 (sim t=5)
    mask[1, 1] = 0.0  # and step 1 (sim t=6)
    agg._log_home_failures(mask)
    name = agg.all_homes[1]["name"]
    path = os.path.join(agg.run_dir, "home_logs", f"{name}.log")
    assert os.path.isfile(path)
    lines = open(path).read().strip().splitlines()
    assert len(lines) == 2
    assert "timestep 5" in lines[0] and "fallback" in lines[0]
    assert "timestep 6" in lines[1]
    # Healthy homes create no files.
    others = os.listdir(os.path.join(agg.run_dir, "home_logs"))
    assert others == [f"{name}.log"]


def test_home_failure_logs_noop_on_clean_chunk(tmp_path):
    cfg = _tiny_cfg()
    agg = Aggregator(cfg, data_dir=None, outputs_dir=str(tmp_path / "out"))
    agg.get_homes()
    agg.set_run_dir()
    agg._log_home_failures(np.ones((2, 3)))
    assert not os.path.isdir(os.path.join(agg.run_dir, "home_logs"))


def test_verbose_chunk_telemetry(tmp_path, caplog, monkeypatch):
    """VERBOSE env enables per-chunk solver telemetry at PROG level
    (dragg/mpc_calc.py:81-86 analog)."""
    monkeypatch.setenv("VERBOSE", "1")
    cfg = _tiny_cfg()
    agg = Aggregator(cfg, data_dir=None, outputs_dir=str(tmp_path / "out"))
    agg.get_homes()
    agg._build_engine()
    agg.reset_collected_data()
    agg.checkpoint_interval = agg._checkpoint_steps()
    agg.set_run_dir()
    import logging

    monkeypatch.setattr(logging.getLogger("dragg_tpu.aggregator"),
                        "propagate", True)  # expose records to caplog
    with caplog.at_level("INFO", logger="dragg_tpu.aggregator"):
        agg.run_baseline()
    msgs = [r.message for r in caplog.records if "solve_rate" in r.message]
    assert msgs, "VERBOSE run must emit chunk solver telemetry"
    assert "ADMM iters" in msgs[0]


def test_reset_seed_changes_population(tmp_path):
    """reset_seed (dragg/aggregator.py:255-261): a different seed produces a
    different (renamed) population on the next synthesis."""
    cfg = _tiny_cfg()
    agg = Aggregator(cfg, data_dir=None, outputs_dir=str(tmp_path / "out"))
    agg.get_homes()
    names1 = [h["name"] for h in agg.all_homes]
    agg.reset_seed(999)
    agg.all_homes = None
    agg.engine = None
    agg.get_homes()
    names2 = [h["name"] for h in agg.all_homes]
    assert names1 != names2


@pytest.mark.slow  # round-11 tier-1 budget trim: profiler-trace plumbing, not correctness; the phase timers stay covered by the bench smoke
def test_profiler_trace_and_phase_times(tmp_path):
    """tpu.profile_dir wraps the second device chunk in a jax.profiler trace
    and Summary carries the wall-clock phase attribution (SURVEY §5.1)."""
    cfg = _tiny_cfg()
    cfg["simulation"]["end_datetime"] = "2015-01-01 08"
    cfg["simulation"]["checkpoint_interval"] = "hourly"  # several chunks
    prof = str(tmp_path / "trace")
    cfg["tpu"]["profile_dir"] = prof
    agg = Aggregator(cfg, data_dir=None, outputs_dir=str(tmp_path / "out"))
    agg.run()
    assert os.path.isdir(prof) and os.listdir(prof), "no profiler trace written"
    import glob as _glob
    import json as _json

    res = _glob.glob(os.path.join(str(tmp_path / "out"), "**", "results.json"),
                     recursive=True)
    summary = _json.load(open(res[0]))["Summary"]
    pt = summary["phase_times"]
    assert pt["device_chunks"] > 0.0
    assert pt["collect"] >= 0.0
