"""Deterministic config-space fuzz: odd horizon/dt/sub-step/mix corners.

The fixed tests pin behavior at the default shapes; this sweeps the shape
knobs the reference exposes (prediction_horizon down to 1 h, subhourly
aggregator steps, sub_subhourly duty cycles, each home-type mix) and
asserts the engine invariants hold at every corner: finite outputs,
box-respecting solved homes, fallback routing for the rest.
"""

import copy

import numpy as np
import pytest

from dragg_tpu.config import default_config
from dragg_tpu.data import load_environment, load_waterdraw_profiles
from dragg_tpu.engine import make_engine
from dragg_tpu.homes import build_home_batch, create_homes

CASES = [
    # (horizon_h, agg_dt, sub_steps, n, pv, batt, pvbatt, seed)
    (1, 1, 1, 4, 1, 1, 0, 1),      # minimum horizon, no duty subdivision
    (1, 2, 6, 4, 1, 1, 1, 2),      # subhourly aggregator steps
    (3, 2, 2, 5, 0, 0, 0, 3),      # base-only community
    (5, 1, 6, 4, 4, 0, 0, 4),      # all-PV
    (2, 1, 6, 4, 0, 4, 0, 5),      # all-battery
    (7, 1, 3, 6, 2, 2, 2, 6),      # odd horizon, every type
]


def _run_corner(h, dt, s, n, pv, bat, pvb, seed, bucketed="auto",
                solver=None):
    cfg = copy.deepcopy(default_config())
    cfg["community"]["total_number_homes"] = n
    cfg["community"]["homes_pv"] = pv
    cfg["community"]["homes_battery"] = bat
    cfg["community"]["homes_pv_battery"] = pvb
    cfg["simulation"]["random_seed"] = seed
    cfg["agg"]["subhourly_steps"] = dt
    cfg["home"]["hems"]["prediction_horizon"] = h
    cfg["home"]["hems"]["sub_subhourly_steps"] = s
    cfg["tpu"]["bucketed"] = bucketed
    if solver is not None:
        cfg["home"]["hems"]["solver"] = solver

    env = load_environment(cfg, data_dir=None)
    wd = load_waterdraw_profiles(None, seed=seed)
    homes = create_homes(cfg, 24 * dt, dt, wd)
    batch = build_home_batch(homes, h * dt, dt, s)
    eng = make_engine(batch, env, cfg, 0)
    state = eng.init_state()
    rps = np.zeros((3, eng.params.horizon), np.float32)
    state, outs = eng.run_chunk(state, 0, rps)

    for field in outs._fields:
        a = np.asarray(getattr(outs, field))
        assert np.isfinite(a).all(), f"{field} not finite at case {h,dt,s}"
    solved = np.asarray(outs.correct_solve).astype(bool)
    # Duty fractions live in [0, 1] wherever the QP solved.
    for duty in ("hvac_cool_on", "hvac_heat_on", "wh_heat_on"):
        d = np.asarray(getattr(outs, duty))[solved]
        assert (d > -1e-3).all() and (d < 1 + 1e-3).all(), duty
    # The thermal state stays inside physically plausible bounds everywhere
    # (fallback bang-bang included).
    ti = np.asarray(outs.temp_in)
    tw = np.asarray(outs.temp_wh)
    assert (ti > -10).all() and (ti < 50).all()
    assert (tw > 0).all() and (tw < 90).all()
    # At least the bulk of home-steps solve at every corner.
    assert solved.mean() > 0.5, f"solve rate {solved.mean():.2f} at {h,dt,s}"
    return eng


@pytest.mark.parametrize("h,dt,s,n,pv,bat,pvb,seed", CASES)
def test_engine_invariants_across_config_corners(h, dt, s, n, pv, bat, pvb, seed):
    _run_corner(h, dt, s, n, pv, bat, pvb, seed)


# Type-mix corners for the bucketed engine (tpu.bucketed), including the
# degenerate bucket shapes: all-base (one reduced bucket), all-pv_battery
# (one superset-shaped bucket), one-home buckets, a type absent entirely,
# and the smallest community where "auto" flips bucketing on.  The engine
# invariants must hold and no zero-width bucket may ever compile.
# The four heaviest corners (one-home buckets, minimum horizon, absent
# type, and the 33-home auto-on community — 45–81 s each on this
# container) ride the slow tier: tier-1 keeps the degenerate bucket
# SHAPES (all-base reduced layout, all-superset bucket, auto-off) and
# the auto thresholds stay unit-covered by
# tests/test_bucketed.py::test_resolve_bucket_plan (round-11 tier-1
# budget trim — the suite had outgrown ROADMAP's 870 s verify window).
BUCKETED_CASES = [
    # (h, dt, s, n, pv, bat, pvb, seed, bucketed, expect_bucketed)
    (2, 1, 4, 5, 0, 0, 0, 7, "true", True),     # all-base
    (2, 1, 6, 4, 0, 0, 4, 8, "true", True),     # all-pv_battery
    pytest.param(3, 1, 6, 4, 1, 1, 1, 9, "true", True,
                 marks=pytest.mark.slow),        # one-home buckets, all types
    pytest.param(1, 2, 2, 5, 2, 0, 2, 10, "true", True,
                 marks=pytest.mark.slow),        # battery_only absent, h*dt=2
    pytest.param(1, 1, 2, 4, 1, 1, 1, 11, "true", True,
                 marks=pytest.mark.slow),        # minimum horizon, tiny buckets
    pytest.param(2, 1, 6, 33, 13, 4, 3, 12, "auto", True,
                 marks=pytest.mark.slow),        # smallest auto-on community
    (2, 1, 6, 33, 0, 0, 33, 13, "auto", False),  # auto off: all-superset
]


@pytest.mark.parametrize("h,dt,s,n,pv,bat,pvb,seed,bucketed,expect", BUCKETED_CASES)
def test_engine_invariants_across_type_mixes(h, dt, s, n, pv, bat, pvb, seed,
                                             bucketed, expect):
    eng = _run_corner(h, dt, s, n, pv, bat, pvb, seed, bucketed=bucketed)
    assert eng.bucketed == expect, (eng.bucketed, expect)
    info = eng.bucket_info()
    assert all(b["n_slots"] > 0 and b["n_real"] > 0 for b in info), info
    if eng.bucketed:
        # Only the types present in the mix become buckets — an absent
        # type must not produce a zero-width compiled bucket.
        present = {t for t, c in (("pv_only", pv), ("battery_only", bat),
                                  ("pv_battery", pvb),
                                  ("base", n - pv - bat - pvb)) if c > 0}
        assert {b["name"] for b in info} == present


# ReLU-QP corners (round 10): the pre-factorized family must hold the
# same invariants over the shape/mix knobs — including the degenerate
# bucket shapes, where every bucket gets its own (B, R, m, m) rho bank.
RELUQP_CASES = [
    CASES[1],           # subhourly steps + every special type
    CASES[2],           # base-only community (reduced layout)
    CASES[5],           # odd horizon, every type
    (2, 1, 6, 33, 13, 4, 3, 12),  # smallest auto-bucketed community
]


@pytest.mark.slow
@pytest.mark.parametrize("h,dt,s,n,pv,bat,pvb,seed", RELUQP_CASES)
def test_engine_invariants_reluqp_type_mixes(h, dt, s, n, pv, bat, pvb,
                                             seed):
    _run_corner(h, dt, s, n, pv, bat, pvb, seed, solver="reluqp")


# Scenario-pack fuzz (ISSUE 10): random-ish mixes including 0-count new
# types, overlapping DR + outage windows, event windows clipped at the
# series/horizon edges, and a C>1 fleet with per-community schedules.
# Every corner asserts the same engine invariants via _run_scenario.
SCENARIO_CASES = [
    # (h, dt, s, n, counts{type: n}, events, communities, seed)
    (2, 1, 4, 6, {"ev": 2, "heat_pump": 2}, [], 1, 21),   # new types, no events
    (2, 1, 6, 6, {"ev": 0, "heat_pump": 0}, [            # 0-count new types +
        dict(kind="dr", start_hour=0, duration_hours=2,  # events on legacy mix
             p_cap_kw=3.0, comfort_relax_degc=1.0)], 1, 22),
    (3, 1, 4, 8, {"pv_only": 2, "ev": 2, "heat_pump": 2}, [
        dict(kind="dr", start_hour=1, duration_hours=3, p_cap_kw=2.0,
             comfort_relax_degc=2.0),
        dict(kind="outage", start_hour=2, duration_hours=2,  # overlaps the DR
             comfort_relax_degc=2.0)], 1, 23),
    (2, 2, 2, 5, {"heat_pump": 5}, [                     # clipped at the edge
        dict(kind="tariff_shock", start_hour=46, duration_hours=1000,
             price_delta=0.2)], 1, 24),
    pytest.param(2, 1, 4, 6, {"pv_battery": 2, "ev": 2}, [
        dict(kind="outage", start_hour=1, duration_hours=2,
             communities=[1], comfort_relax_degc=3.0),
        dict(kind="tariff_shock", start_hour=0, duration_hours=6,
             communities=[0], price_delta=0.1)], 2, 25,
        marks=pytest.mark.slow),                         # C=2 fleet schedules
]


@pytest.mark.parametrize("h,dt,s,n,counts,events,comm,seed", SCENARIO_CASES)
def test_engine_invariants_scenario_packs(h, dt, s, n, counts, events,
                                          comm, seed):
    from dragg_tpu.data import load_waterdraw_profiles as _wd
    from dragg_tpu.engine import make_engine as _mk
    from dragg_tpu.homes import build_fleet_batch, create_fleet_homes

    from dragg_tpu.scenarios import MIX_KEYS

    cfg = copy.deepcopy(default_config())
    cfg["community"]["total_number_homes"] = n
    for key in MIX_KEYS.values():
        cfg["community"][key] = 0  # the cases name their counts explicitly
    for t, c in counts.items():
        cfg["community"][MIX_KEYS[t]] = c
    cfg["simulation"]["random_seed"] = seed
    cfg["agg"]["subhourly_steps"] = dt
    cfg["home"]["hems"]["prediction_horizon"] = h
    cfg["home"]["hems"]["sub_subhourly_steps"] = s
    cfg["tpu"]["fix_tou_peak"] = True  # shocks compose with the fixed ladder
    cfg["fleet"]["communities"] = comm
    cfg["scenarios"]["events"] = events

    env = load_environment(cfg, data_dir=None)
    wd = _wd(None, seed=seed)
    homes = create_fleet_homes(cfg, 48 * dt, dt, wd)
    batch, fleet = build_fleet_batch(homes, cfg, h * dt, dt, s)
    eng = _mk(batch, env, cfg, 0, fleet=fleet)
    state = eng.init_state()
    rps = np.zeros((3, eng.params.horizon), np.float32)
    state, outs = eng.run_chunk(state, 0, rps)

    for field in outs._fields:
        a = np.asarray(getattr(outs, field))
        assert np.isfinite(a).all(), f"{field} not finite"
    solved = np.asarray(outs.correct_solve).astype(bool)
    for duty in ("hvac_cool_on", "hvac_heat_on", "wh_heat_on"):
        d = np.asarray(getattr(outs, duty))[solved]
        assert (d > -1e-3).all() and (d < 1 + 1e-3).all(), duty
    # EV SOC stays physical everywhere; non-EV homes stay at exactly 0.
    cols = eng.real_home_cols
    e_ev = np.asarray(outs.e_ev)[:, cols]
    cap = np.asarray(batch.ev_cap)[np.argsort(np.asarray(
        fleet.global_idx))] if fleet is not None else np.asarray(batch.ev_cap)
    assert (e_ev >= -1e-4).all() and (e_ev <= cap[None] + 1e-3).all()
    is_ev = np.asarray(batch.is_ev)
    is_ev = is_ev[np.argsort(np.asarray(fleet.global_idx))] \
        if fleet is not None else is_ev
    assert np.all(e_ev[:, is_ev == 0] == 0.0)
    # Event-free corners must keep the full solve rate ballpark.  Evented
    # corners legitimately route homes to the fallback (outage islanding
    # of all-electric homes, binding DR caps) — the floor there only
    # guards against EVERYTHING failing, and the first (pre-event or
    # evented-but-feasible) step must still mostly solve.
    floor = 0.25 if events else 0.8
    assert solved.mean() > floor, f"solve rate {solved.mean():.2f}"
    assert solved[0].mean() > 0.5, "step 0 collapsed"


def test_shipped_example_config_matches_defaults():
    """data/config.example.toml (the reference ships an editable
    config.toml — dragg/data/config.toml — so we ship a starting-point
    example) must parse to EXACTLY default_config(): the example a user
    copies can never drift from the shipped defaults.  Named .example so
    the live default-config resolution ($DATA_DIR/config.toml, default
    data/) never silently picks it up — a user's edited copy must not be
    able to fail the suite or change repo-root run behavior (advisor
    finding, r4)."""
    import os

    from dragg_tpu.config import load_config

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "data", "config.example.toml")
    loaded = load_config(path)
    assert loaded == default_config()
