"""Fleet trace plane (round 20 — ISSUE 20, architecture.md §21).

The tentpole contract has two halves and both are pinned here:

* **off-mode byte identity** — with tracing off (the default), the
  trace layer adds NOTHING: no envelope fields, no env exports, no
  headers; the round-19 events.jsonl shape is byte-identical (the seed
  invariant every satellite rides on);
* **on-mode completeness** — a traced run assembles into causal trees
  with >= 1 root and ZERO orphan spans across every propagation edge:
  supervisor -> child (env), serve request -> batch -> worker chunk
  (HTTP + env), and tcp shard chunk -> coordinator merge (wire frame),
  the last one surviving a kill -9 mid-chunk plus relaunch.

Around them: the ``(t, pid, seq)`` + clock-skew merge ordering, the
periodic metrics flush (crash loses at most one interval — chaos-pinned
with a real SIGKILL), the /rollup.json + /metrics fleet view, the
serve-side phase decomposition, and the doctor's trace-plane selftest.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from dragg_tpu import telemetry
from dragg_tpu.config import default_config
from dragg_tpu.resilience import faults
from dragg_tpu.telemetry import rollup, trace, traces

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ENVELOPE = {"event", "t", "mono", "pid", "seq"}


@pytest.fixture(autouse=True)
def _isolated_trace_plane(monkeypatch):
    """Every test starts and ends with no bus, no trace context, and no
    trace/flush env (trace.enable() and the coordinator/daemon flush
    export are process-global, so leakage would couple tests)."""
    monkeypatch.delenv(trace.ENV_CTX, raising=False)
    monkeypatch.delenv(telemetry.ENV_FLUSH, raising=False)
    telemetry.close_run()
    trace.disable()
    yield
    telemetry.close_run()
    trace.disable()
    faults.reset_plan()


# ------------------------------------------------- off-mode byte identity
def test_off_mode_stream_is_round19_byte_identical(tmp_path):
    """Tracing off adds NO fields anywhere: every helper returns its
    empty sentinel, and an emitted stream's records carry EXACTLY the
    round-19 envelope plus the caller's fields — no trace/span/parent
    keys for the assembler to find."""
    assert trace.current() is None and not trace.enabled()
    assert trace.env_value() is None
    assert trace.child_fields() == {}
    assert trace.child_fields(parent="x") == {}
    assert trace.span_fields("s1") == {}

    telemetry.init_run(str(tmp_path))
    # The exact emit shapes the shard/serve layers use, including the
    # **child_fields() splat that must expand to nothing.
    telemetry.emit("run.start", case="baseline", homes=3, horizon=2,
                   solver="ipm", run_dir=str(tmp_path))
    telemetry.emit("chunk.done", t0=0, t1=2, solve_rate=1.0, device_s=0.1,
                   **trace.child_fields())
    telemetry.emit("wire.push", shard=0, seq=0, dup=False, attempts=1,
                   **trace.child_fields(parent="ignored-when-off"))
    telemetry.emit("run.end", completed=True)
    telemetry.close_run()

    recs = [json.loads(l) for l in
            open(os.path.join(str(tmp_path), telemetry.EVENTS_FILE))]
    expected_keys = [
        ENVELOPE | {"case", "homes", "horizon", "solver", "run_dir"},
        ENVELOPE | {"t0", "t1", "solve_rate", "device_s"},
        ENVELOPE | {"shard", "seq", "dup", "attempts"},
        ENVELOPE | {"completed"},
    ]
    assert [set(r) for r in recs] == expected_keys
    rep = traces.trace_report(str(tmp_path))
    assert rep["traces"] == {} and rep["untraced_records"] == 4


def test_trace_context_enable_and_env_join(monkeypatch):
    """enable() mints trace + process-root span; a child process joins
    the SAME trace lazily from $DRAGG_TRACE_CTX, minting its own root
    span parented on the exported one (how supervised children land
    inside the parent's tree without calling enable())."""
    ctx = trace.enable()
    assert trace.enabled() and trace.current() == ctx
    assert ctx["parent"] is None
    assert trace.env_value() == f"{ctx['trace']}:{ctx['span']}"
    assert trace.env_value(span="abc") == f"{ctx['trace']}:abc"
    kid = trace.child_fields()
    assert kid["parent"] == ctx["span"] and kid["span"] != ctx["span"]
    assert trace.child_fields(parent="p1")["parent"] == "p1"
    assert trace.span_fields("s1") == {"span": "s1"}
    assert trace.span_fields("s1", parent="p2") == \
        {"span": "s1", "parent": "p2"}

    # Simulated child: fresh module state + the exported env value.
    trace.disable()
    monkeypatch.setenv(trace.ENV_CTX, f"{ctx['trace']}:{ctx['span']}")
    joined = trace.current()
    assert joined["trace"] == ctx["trace"]
    assert joined["parent"] == ctx["span"]
    assert joined["span"] not in (ctx["span"], None)


# --------------------------------------------------- merge order + skew
def _write_stream(run_dir, recs):
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, telemetry.EVENTS_FILE), "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def test_merged_ordering_t_pid_seq_with_skew(tmp_path):
    """tail_events_dir orders the merged streams by skew-corrected
    (t, pid, seq): a shard whose trace.skew says its wall clock runs
    5 s FAST sorts 5 s earlier, and exact-t ties break by pid then
    per-process seq — deterministic cross-process interleave."""
    main = str(tmp_path)
    _write_stream(main, [
        {"event": "shard.plan", "t": 100.0, "pid": 10, "seq": 1},
        # Exact-t tie with the pid-20 record below: pid breaks it.
        {"event": "shard.merge", "t": 104.0, "pid": 10, "seq": 2},
        {"event": "shard.merge", "t": 104.0, "pid": 10, "seq": 3},
    ])
    _write_stream(os.path.join(main, "shard0"), [
        {"event": "trace.skew", "t": 101.0, "pid": 20, "seq": 1,
         "shard": 0, "offset_s": -5.0, "rtt_s": 0.001},
        {"event": "chunk.done", "t": 102.0, "pid": 20, "seq": 2, "t1": 2},
        {"event": "chunk.done", "t": 109.0, "pid": 20, "seq": 3, "t1": 4},
    ])
    merged = telemetry.tail_events_dir(
        os.path.join(main, telemetry.EVENTS_FILE), limit=10)
    assert [(r["_stream"], r["seq"]) for r in merged] == [
        ("shard0", 1),   # 101 - 5 = 96
        ("shard0", 2),   # 102 - 5 = 97
        ("main", 1),     # 100
        ("main", 2),     # 104, pid 10 before pid 20's 104
        ("main", 3),     # same t+pid -> seq
        ("shard0", 3),   # 109 - 5 = 104, pid 20
    ]
    # Without the skew record, wall clocks are trusted as-is (the
    # documented multi-host caveat) — the shard sorts between.
    offs = telemetry.skew_offsets(merged)
    assert offs == {("shard0", 20): -5.0}


# -------------------------------------------------- live metrics flush
def test_flush_interval_writes_live_snapshot(tmp_path):
    """flush_s > 0 persists metrics.json DURING the run (time-gated on
    emit) — the live-rollup feed; the default 0.0 keeps the round-19
    close-time-only behavior."""
    telemetry.init_run(str(tmp_path / "off"))
    telemetry.inc("engine.repair_failed")
    telemetry.emit("heartbeat.beat", progress={})
    assert not os.path.exists(
        os.path.join(str(tmp_path / "off"), telemetry.METRICS_FILE))
    telemetry.close_run()

    telemetry.init_run(str(tmp_path / "on"), flush_s=0.01)
    telemetry.inc("engine.repair_failed", 3)
    time.sleep(0.02)
    telemetry.emit("heartbeat.beat", progress={})  # crosses the gate
    path = os.path.join(str(tmp_path / "on"), telemetry.METRICS_FILE)
    assert os.path.exists(path), "no in-progress flush before close"
    snap = json.load(open(path))
    assert snap["counters"]["engine.repair_failed"] == 3


def test_flush_survives_sigkill(tmp_path):
    """The crash-safety point of the flush: a child that is SIGKILL'd
    mid-run (no close, no atexit) still leaves its last flushed
    metrics.json for the coordinator's post-mortem."""
    child = (
        "import os, signal, sys, time\n"
        "sys.path.insert(0, %r)\n"
        "from dragg_tpu import telemetry\n"
        "telemetry.init_run(%r, flush_s=0.01)\n"
        "telemetry.inc('engine.repair_failed', 7)\n"
        "time.sleep(0.02)\n"
        "telemetry.emit('heartbeat.beat', progress={})\n"
        "os.kill(os.getpid(), signal.SIGKILL)\n" % (ROOT, str(tmp_path)))
    proc = subprocess.run([sys.executable, "-c", child],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == -signal.SIGKILL
    snap = json.load(open(os.path.join(str(tmp_path),
                                       telemetry.METRICS_FILE)))
    assert snap["counters"]["engine.repair_failed"] == 7


# --------------------------------------------------------------- rollup
def test_rollup_fold_and_prometheus(tmp_path):
    """fold_rollup merges per-stream snapshots + tails into the fleet
    view (summed counters, per-shard scoreboard with frontier lag and
    wire counters); prometheus_text exposes it as 0.0.4 text."""
    run_dir = str(tmp_path)
    telemetry.init_run(run_dir)
    telemetry.emit("shard.plan", workers=2, communities=2)
    telemetry.emit("shard.launch", shard=0, gen=1, platform="cpu")
    telemetry.emit("shard.chunk", shard=1, seq=0, t0=0, t1=2)
    telemetry.inc("wire.dedup", 1)          # server-side dup surface
    telemetry.set_gauge("engine.solve_rate", 0.5)
    telemetry.write_snapshot()
    telemetry.close_run()
    telemetry.init_run(os.path.join(run_dir, "shard0"))
    telemetry.emit("chunk.done", t0=0, t1=4, solve_rate=1.0)
    telemetry.inc("wire.retries", 2)
    telemetry.inc("engine.repair_failed", 1)
    telemetry.write_snapshot()
    telemetry.close_run()

    roll = rollup.fold_rollup(run_dir, now=time.time())
    assert set(roll["streams"]) == {"main", "shard0"}
    assert roll["fleet_counters"]["wire.retries"] == 2
    assert roll["fleet_counters"]["engine.repair_failed"] == 1
    assert roll["wire_dedup_server"] == 1
    assert roll["frontier_t"] == 4
    rows = {r["shard"]: r for r in roll["shards"]}
    # shard0 has a live stream + snapshot; shard1 is known only from
    # the coordinator's merge record (the lost-stream fallback).
    assert rows["shard0"]["frontier_t"] == 4
    assert rows["shard0"]["frontier_lag"] == 0
    assert rows["shard0"]["wire_retries"] == 2
    assert rows["shard0"]["platform"] == "cpu"
    assert rows["shard0"]["metrics_written_at"] is not None
    assert rows["shard0"]["last_event_age_s"] is not None
    assert rows["shard1"]["frontier_t"] == 2
    assert rows["shard1"]["frontier_lag"] == 2

    text = rollup.prometheus_text(roll)
    assert "# TYPE dragg_wire_retries counter" in text
    assert "# TYPE dragg_engine_solve_rate gauge" in text
    assert 'dragg_wire_retries{stream="shard0"} 2.0' in text
    assert 'dragg_shard_frontier_lag{shard="shard1"} 2.0' in text
    assert 'dragg_fleet_frontier_t{run="current"} 4.0' in text


# ------------------------------------------ propagation: supervisor/env
def test_supervisor_child_lands_in_parent_trace(tmp_path):
    """Env edge: run_supervised exports $DRAGG_TRACE_CTX, the child's
    first emit joins lazily — one trace, one rooted tree, zero orphans,
    and the child's span parented on the supervisor's root."""
    from dragg_tpu.resilience.supervisor import run_supervised

    ctx = trace.enable()
    telemetry.init_run(str(tmp_path))
    child = ("import sys; sys.path.insert(0, %r); "
             "from dragg_tpu.resilience.heartbeat import beat; "
             "beat({'stage': 'traced-child'})" % ROOT)
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    res = run_supervised([sys.executable, "-c", child], deadline_s=60.0,
                         label="trace-child", env=env)
    assert res.ok, res.stderr_tail
    telemetry.close_run()

    records = traces.read_records(str(tmp_path))
    assert all(r.get("trace") == ctx["trace"] for r in records)
    rep = traces.trace_report(str(tmp_path), records=records)
    assert rep["complete"], traces.completeness_problems(rep)
    tr = traces.assemble(records)["traces"][ctx["trace"]]
    assert tr["roots"] == [ctx["span"]] and tr["orphans"] == []
    beat = next(r for r in records if r["event"] == "heartbeat.beat")
    assert beat["pid"] != os.getpid()
    assert tr["spans"][beat["span"]]["parent"] == ctx["span"]


# --------------------------------------------- propagation: serve/HTTP
def _serve_cfg(**overrides):
    cfg = default_config()
    cfg["serve"].update({"port": 0, "poll_s": 0.02, "backoff_s": 0.1,
                         "request_retries": 3, "batch_deadline_s": 30.0,
                         "worker_stall_s": 30.0, "drain_s": 10.0,
                         **overrides})
    cfg["telemetry"]["trace"] = True
    # Live flush so /metrics has per-stream snapshots mid-run.
    cfg["telemetry"]["flush_interval_s"] = 0.05
    return cfg


def _request(base, path, body=None, headers=None):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode() if body is not None else None,
        headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def test_serve_request_to_worker_chunk_rooted_tree(tmp_path):
    """HTTP + env edge: a traced daemon answers X-Dragg-Trace/Span on
    the 202, records the client's X-Dragg-Parent informationally, and
    the request -> batch -> worker serve.chunk -> serve.done chain
    assembles into ONE rooted tree with zero orphans — the worker's
    chunk spans crossing the process boundary via the batch payload."""
    from dragg_tpu.serve.daemon import ServeDaemon

    sdir = str(tmp_path / "serve")
    d = ServeDaemon(_serve_cfg(), sdir, platform="cpu", stub=True)
    d.start()
    try:
        base = f"http://127.0.0.1:{d.port}"
        # steps=2 so the worker emits per-step serve.chunk records (the
        # cross-process leg of the tree; single-step solves skip them).
        code, hdrs, raw = _request(
            base, "/solve", {"id": "tr1", "t": 0, "home": 2, "steps": 2},
            headers={"X-Dragg-Parent": "client-span-42"})
        assert code == 202
        tid = hdrs.get("X-Dragg-Trace")
        rspan = hdrs.get("X-Dragg-Span")
        assert tid and rspan, "202 missing trace response headers"
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            _c, _h, body = _request(base, "/result?id=tr1")
            if json.loads(body).get("status") in ("done", "failed"):
                break
            time.sleep(0.05)
        assert json.loads(body)["status"] == "done"

        # Live fleet view over the same socket while the run is open.
        code, _h, roll = _request(base, "/rollup.json")
        roll = json.loads(roll)
        assert code == 200 and "main" in roll["streams"]
        code, _h, prom = _request(base, "/metrics")
        assert code == 200 and b"# TYPE dragg_" in prom
    finally:
        d.stop(drain=False)

    records = traces.read_records(sdir)
    rep = traces.trace_report(sdir, records=records)
    assert rep["complete"], traces.completeness_problems(rep)
    assert list(rep["traces"]) == [tid]
    req_rec = next(r for r in records if r["event"] == "serve.request")
    assert req_rec["span"] == rspan
    assert req_rec["client_parent"] == "client-span-42"
    tr = traces.assemble(records)["traces"][tid]
    assign = next(r for r in records if r["event"] == "serve.assign")
    assert assign["parent"] == rspan, "batch span not parented on request"
    chunk = next(r for r in records if r["event"] == "serve.chunk")
    assert chunk["parent"] == assign["span"], \
        "worker chunk span not parented on the batch payload span"
    assert chunk["pid"] != os.getpid(), "chunk must come from the worker"
    done = next(r for r in records if r["event"] == "serve.done")
    assert done["span"] == rspan, "serve.done must close the request span"
    assert tr["spans"][chunk["span"]]["streams"] == ["main"]

    # Server-side phase decomposition (tools/serve_load.py satellite).
    phases = traces.phase_breakdown(records, ["tr1"])["tr1"]
    assert phases["queue_s"] is not None and phases["queue_s"] >= 0.0
    assert phases["solve_s"] is not None and phases["solve_s"] >= 0.0


# ------------------------------------------- propagation: shard wire/tcp
def _shard_cfg(C=2, n=6):
    """test_shard's composition-invariant pinned config, telemetry ON
    (the trace plane is the subject here, not parity)."""
    cfg = default_config()
    cfg["community"]["total_number_homes"] = n
    cfg["community"]["homes_pv"] = 1
    cfg["community"]["homes_battery"] = 1
    cfg["community"]["homes_pv_battery"] = 1
    cfg["home"]["hems"]["prediction_horizon"] = 2
    cfg["home"]["hems"]["solver"] = "ipm"
    cfg["fleet"]["communities"] = C
    cfg["fleet"]["seed_stride"] = 5
    cfg["tpu"]["bucketed"] = "false"
    cfg["tpu"]["ipm_tail_frac"] = 0.0
    cfg["tpu"]["sharded"] = False
    cfg["telemetry"] = {"enabled": True, "trace": True,
                        "flush_interval_s": 0.05}
    return cfg


def test_tcp_shard_trace_complete_across_kill9(tmp_path, monkeypatch):
    """Wire edge + the acceptance headline in one coordinator run: a
    traced 2-shard tcp run with one worker SIGKILL'd mid-chunk still
    assembles to ONE complete tree (chunk spans ride the frame body to
    the coordinator's merge; the relaunched generation re-joins the same
    trace via env), the clock handshake leaves trace.skew records, and
    the per-chunk flush keeps every shard's metrics.json live."""
    from dragg_tpu.shard.coordinator import run_sharded

    cfg = _shard_cfg(C=2)
    cfg["shard"] = {"transport": "tcp"}
    monkeypatch.setenv(telemetry.ENV_FLUSH, "0.05")
    monkeypatch.setenv("DRAGG_FAULT_INJECT", "sigkill@shard_chunk:2:once")
    monkeypatch.setenv("DRAGG_FAULT_STATE", str(tmp_path / "faults"))
    os.makedirs(str(tmp_path / "faults"), exist_ok=True)
    faults.reset_plan()
    run_dir = str(tmp_path / "run")
    res = run_sharded(cfg, run_dir=run_dir, steps=4, workers=2,
                      chunk_steps=2, platform="cpu", data_dir="")
    assert sum(res["restarts"].values()) == 1, "chaos never fired"

    records = traces.read_records(run_dir)
    rep = traces.trace_report(run_dir, records=records)
    assert rep["complete"], traces.completeness_problems(rep)
    assert len(rep["traces"]) == 1
    tid, meta = next(iter(rep["traces"].items()))
    assert len(meta["roots"]) == 1 and not meta["orphans"]

    # Every layer of the chain is present and trace-stamped.
    by_event = {}
    for r in records:
        by_event.setdefault(r["event"], []).append(r)
    for ev in ("shard.plan", "shard.launch", "chunk.done", "wire.push",
               "wire.ingest", "shard.chunk", "trace.skew"):
        assert ev in by_event, f"traced run missing {ev}"
        assert all(r.get("trace") == tid for r in by_event[ev]), ev
    # wire.push carries its wall seconds for the critical path, and the
    # merge record parents on the SAME chunk span the worker opened.
    assert all(r.get("s") is not None for r in by_event["wire.push"])
    chunk_spans = {r["span"] for r in by_event["chunk.done"]}
    assert {r["parent"] for r in by_event["shard.chunk"]} <= chunk_spans
    # Critical path attributes device + wire seconds.
    cp = rep["traces"][tid]["critical_path"]
    assert cp["path_seconds"].get("device", 0) > 0
    # Handshake offsets are ~0 on one host but must be RECORDED.
    assert {r["shard"] for r in by_event["trace.skew"]} == {0, 1}
    # Per-chunk flush: both shard sub-streams left live snapshots.
    for k in (0, 1):
        snap = json.load(open(os.path.join(run_dir, f"shard{k}",
                                           telemetry.METRICS_FILE)))
        assert snap["counters"] or snap["gauges"] or snap["histograms"]


# ---------------------------------------------------------------- doctor
def test_doctor_trace_plane_selftest():
    """doctor --telemetry's check: traced child -> complete tree, live
    flush observed before close, rollup folds — all in one subprocess."""
    from dragg_tpu.doctor import _check_trace_plane

    res = _check_trace_plane(timeout_s=60.0)
    assert res["status"] == "ok", res
    assert res["traces"] == 1 and res["live_flush"] is True
