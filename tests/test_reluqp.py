"""ReLU-QP solver family (``hems.solver = "reluqp"``, ops/reluqp.py) —
parity, plumbing, and the round-10 satellites.

Parity follows the tests/test_qp_parity.py convention: compare OBJECTIVES
against scipy's HiGHS on identical matrices, never iterates.  The engine
equivalence tests follow tests/test_bucketed.py (objectives + applied
actions + physical state, bucketed mapped back to community order).
"""

import copy
import json
import os
import subprocess
import sys

import numpy as np
import pytest
from scipy.optimize import linprog

import jax.numpy as jnp

from dragg_tpu.config import default_config
from dragg_tpu.fixtures import assemble_community_qp
from dragg_tpu.ops.qp import densify_A
from dragg_tpu.ops.reluqp import (
    bank_factor_flops,
    bank_rhos,
    equilibrated_spd_inverse,
    init_reluqp_carry,
    iteration_flops,
    reluqp_solve_qp,
    reluqp_solve_qp_cached,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- flops model
def test_iteration_flops_hand_count():
    """Acceptance: ``flops_per_step`` for reluqp runs is the EXACT dense-
    iteration count.  Hand count for (m, n) = (3, 5), one home, one
    iteration (module docstring of ops/reluqp.py):

        Â (D⁻¹ rhs):  (3, 5) @ (5,)  =  15 MACs = 30 flops
        S⁻¹ t:        (3, 3) @ (3,)  =   9 MACs = 18 flops
        Âᵀ ν:         (5, 3) @ (3,)  =  15 MACs = 30 flops
                                           total = 78 flops
    """
    assert iteration_flops(3, 5) == 78.0
    # The production bucket shape at H=24 (superset: m=77, n=221).
    assert iteration_flops(77, 221) == 4 * 77 * 221 + 2 * 77 * 77
    # Bank build: R dense factorizations at the ADMM's (1/3 + 1 + 1)·m³
    # per-factor model.
    assert bank_factor_flops(3, 4) == pytest.approx(4 * (7 / 3) * 27)


def test_bank_rhos_schedule():
    """The geometric schedule is centered on rho0 (bank//2 entries below,
    the rest above) — config docs, tests, and the solver share this
    helper."""
    rhos = bank_rhos(0.1, 6.0, 5)
    assert rhos.shape == (5,)
    assert rhos[2] == pytest.approx(0.1)        # center entry = rho0
    np.testing.assert_allclose(rhos[1:] / rhos[:-1], 6.0, rtol=1e-12)


def test_equilibrated_spd_inverse():
    """The sanctioned dense-inverse route: SPD batches invert to machine
    accuracy; a singular member is rescued by the relative Tikhonov
    retry; a non-finite member (the practical float32 condition failure)
    is identity-scaled with ok=False — downstream matmuls stay finite
    either way."""
    rng = np.random.RandomState(0)
    A = rng.randn(4, 6, 6).astype(np.float32)
    S = np.einsum("bij,bkj->bik", A, A) + 6 * np.eye(6, dtype=np.float32)
    S[2] = 0.0       # singular — the Tikhonov bump makes it factorizable
    S[3, 0, 0] = np.nan  # non-finite — unrecoverable, identity fallback
    Sinv, ok = equilibrated_spd_inverse(jnp.asarray(S))
    Sinv = np.asarray(Sinv)
    ok = np.asarray(ok)
    assert ok[0] and ok[1] and ok[2] and not ok[3]
    for b in range(2):
        np.testing.assert_allclose(S[b] @ Sinv[b], np.eye(6),
                                   atol=5e-4, rtol=5e-4)
    np.testing.assert_array_equal(Sinv[3], np.eye(6))
    assert np.isfinite(Sinv).all()


# ------------------------------------------------------- HiGHS parity (LP)
def _linprog_reference(A_eq, b_eq, l, u, q):
    bounds = [(lo if np.isfinite(lo) else None,
               hi if np.isfinite(hi) else None) for lo, hi in zip(l, u)]
    return linprog(q, A_eq=A_eq, b_eq=b_eq, bounds=bounds, method="highs")


def _parity_check(horizon_hours, iters):
    """≤1 % objective gap vs HiGHS, home by home, on the real t=0 mixed
    community QP (the default fixture mix is 3 base + 1 pv_only +
    1 battery_only + 1 pv_battery — all four home types)."""
    qp, pat, _lay, _s = assemble_community_qp(
        horizon_hours=horizon_hours, n_homes=6, season="heat")
    sol = reluqp_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                          iters=iters, eps_abs=1e-4, eps_rel=1e-4)
    A = np.asarray(densify_A(pat, qp.vals), dtype=np.float64)
    beq = np.asarray(qp.b_eq, np.float64)
    l = np.asarray(qp.l_box, np.float64)
    u = np.asarray(qp.u_box, np.float64)
    q = np.asarray(qp.q, np.float64)
    x = np.asarray(sol.x, np.float64)
    solved = np.asarray(sol.solved)
    n_checked = 0
    for i in range(A.shape[0]):
        ref = _linprog_reference(A[i], beq[i], l[i], u[i], q[i])
        if not ref.success:
            assert not solved[i]
            continue
        assert solved[i], (
            f"home {i} unsolved (r_prim={float(sol.r_prim[i]):.2e})")
        gap = (float(q[i] @ x[i]) - ref.fun) / max(abs(ref.fun), 1e-3)
        assert gap < 0.01, f"home {i}: cost gap {gap:.4%}"
        assert gap > -0.005, f"home {i}: 'beat' the optimum — violation"
        viol = np.max(np.abs(A[i] @ x[i] - beq[i]))
        assert viol < 1e-2, f"home {i}: equality violation {viol}"
        n_checked += 1
    assert n_checked >= 4


def test_reluqp_matches_highs_all_types():
    _parity_check(horizon_hours=4, iters=4000)


@pytest.mark.slow
def test_reluqp_parity_24h_horizon():
    _parity_check(horizon_hours=24, iters=3000)


@pytest.mark.slow
def test_reluqp_infeasibility_certificate():
    """A WH comfort box pinned above the initial temperature is primal-
    infeasible: the banked loop must certify it (OSQP §3.4 — the same
    construction as ops/admm.py) and HiGHS must agree."""
    from dragg_tpu.ops.qp import QPLayout

    qp, pat, _lay, _s = assemble_community_qp(
        horizon_hours=4, n_homes=6, season="heat")
    l = np.asarray(qp.l_box).copy()
    u = np.asarray(qp.u_box).copy()
    H = (pat.n - 5) // 9
    lay = QPLayout(H)
    b0 = float(np.asarray(qp.b_eq)[0, lay.r_twh0])
    l[0, lay.i_twh: lay.i_twh + H + 1] = b0 + 5.0
    sol = reluqp_solve_qp(pat, qp.vals, qp.b_eq, jnp.asarray(l),
                          jnp.asarray(u), qp.q, iters=4000)
    assert not np.asarray(sol.solved)[0]
    assert np.asarray(sol.infeasible)[0]
    A0 = np.asarray(densify_A(pat, qp.vals)[0], np.float64)
    ref = _linprog_reference(
        A0, np.asarray(qp.b_eq[0], np.float64), l[0].astype(np.float64),
        u[0].astype(np.float64), np.asarray(qp.q[0], np.float64))
    assert not ref.success


def test_reluqp_cached_carry_roundtrip():
    """MPC-mode contract: a warm-started no-refresh solve on the carried
    (stale-free here — same matrices) bank reaches the same objectives as
    the one-shot solve, in far fewer iterations, and reports which homes
    needed the fallback tail."""
    qp, pat, _lay, _s = assemble_community_qp(
        horizon_hours=4, n_homes=6, season="heat")
    B = qp.vals.shape[0]
    carry0 = init_reluqp_carry(B, pat, bank=5)
    sol1, c1 = reluqp_solve_qp_cached(
        pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
        carry0, jnp.asarray(True), iters=3000)
    assert np.asarray(sol1.solved).all()
    assert np.asarray(c1.Sinv_bank).shape == (B, 5, pat.m, pat.m)
    sol2, _c2 = reluqp_solve_qp_cached(
        pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
        c1, jnp.asarray(False), iters=3000,
        x0=sol1.x, y_box0=sol1.y_box, rho_warm=sol1.rho)
    assert np.asarray(sol2.solved).all()
    assert int(sol2.iters) < int(sol1.iters)
    q64 = np.asarray(qp.q, np.float64)
    o1 = (q64 * np.asarray(sol1.x, np.float64)).sum(1)
    o2 = (q64 * np.asarray(sol2.x, np.float64)).sum(1)
    np.testing.assert_allclose(o2, o1, rtol=1e-2, atol=5e-3)
    assert np.asarray(sol1.bank_fallback).dtype == bool
    # The final rho is always a bank entry (adaptation = index switch).
    rhos = bank_rhos(0.1, 6.0, 5).astype(np.float32)
    assert np.isin(np.asarray(sol1.rho), rhos).all()


# ---------------------------------------------------- config/engine plumbing
def test_solver_registry_and_engine_params():
    """config.resolve_solver_family: the registry accepts the new family,
    maps reference names, and rejects junk; engine_params threads the
    tuning keys through."""
    from dragg_tpu.config import ConfigError, resolve_solver_family
    from dragg_tpu.engine import engine_params

    cfg = default_config()
    cfg["home"]["hems"]["solver"] = "reluqp"
    assert resolve_solver_family(cfg) == "reluqp"
    p = engine_params(cfg, 0)
    assert p.solver == "reluqp"
    assert (p.reluqp_rho, p.reluqp_rho_factor, p.reluqp_bank,
            p.reluqp_iters, p.reluqp_tail_iters) == (0.1, 6.0, 5, 2000, 300)
    cfg["tpu"]["reluqp_bank"] = 7
    cfg["tpu"]["reluqp_iters"] = 500
    p = engine_params(cfg, 0)
    assert p.reluqp_bank == 7 and p.reluqp_iters == 500
    cfg["home"]["hems"]["solver"] = "GLPK_MI"
    assert resolve_solver_family(cfg) == "ipm"
    cfg["home"]["hems"]["solver"] = "simplex"
    with pytest.raises(ConfigError, match="solver"):
        resolve_solver_family(cfg)


def test_solver_scoped_compile_cache_key(tmp_path, monkeypatch):
    """Satellite regression: the persistent-cache directory is keyed by
    solver family (and the reluqp rho-bank shape), so ipm/admm/reluqp
    executables for the same bucket pattern never share an LRU domain or
    an entry-count attribution window (compile_obs._cache_entries)."""
    from dragg_tpu.utils import compile_cache as cc

    monkeypatch.setenv("DRAGG_COMPILE_CACHE_DIR", str(tmp_path))
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)

    def cfg(solver, **tpu):
        return {"home": {"hems": {"solver": solver}}, "tpu": tpu}

    dirs = {s: cc._resolve_cache_dir(cfg(s))[1]
            for s in ("ipm", "admm", "reluqp")}
    assert len(set(dirs.values())) == 3
    for s, d in dirs.items():
        assert d.startswith(str(tmp_path))
    assert os.path.basename(dirs["ipm"]) == "ipm"
    assert os.path.basename(dirs["reluqp"]) == "reluqp-bank5"
    # The rho-bank shape is part of the key: a different bank size changes
    # every solver executable's shapes.
    assert (cc._resolve_cache_dir(cfg("reluqp", reluqp_bank=9))[1]
            != dirs["reluqp"])
    # Reference names share their mapped family's scope.
    assert cc._resolve_cache_dir(cfg("GLPK_MI"))[1] == dirs["ipm"]
    # No config → shared scope (still host-fingerprint-segregated).
    base, shared, owned = cc._resolve_cache_dir(None)
    assert os.path.basename(shared) == "shared" and owned
    assert cc.solver_cache_scope(None) == "shared"


def _trend(tmp_path, artifacts):
    """tools/bench_trend.py --gate over explicit artifacts; returns
    (rc, parsed JSON line) — the test_observatory helper pattern."""
    paths = []
    for i, obj in enumerate(artifacts):
        p = tmp_path / f"BENCH_r{i + 1:02d}.json"
        p.write_text(json.dumps(obj))
        paths.append(str(p))
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "bench_trend.py"),
         *paths, "--gate"],
        capture_output=True, text=True, timeout=60, cwd=ROOT)
    return proc.returncode, json.loads(proc.stdout.strip().splitlines()[-1])


def test_trend_gate_solver_is_a_hard_key(tmp_path):
    """Satellite: reluqp rows form their own trend series.  A reluqp
    artifact that is 5x slower than the ipm history must NOT read as a
    regression (different hard key); a regression WITHIN the reluqp
    series must still gate."""
    def line(solver, value, solve):
        return dict(metric="m", platform="cpu", solver=solver, value=value,
                    semantics="integer", data="bundled",
                    phase_s_per_step={"solve": solve})

    # ipm history then a (slower) first reluqp artifact: no comparable
    # pair at all — the gate passes.
    rc, trend = _trend(tmp_path, [line("ipm", 10.0, 0.1),
                                  line("reluqp", 2.0, 0.5)])
    assert rc == 0 and trend["rows"] == []
    # Two reluqp artifacts pair up within their own series.
    rc, trend = _trend(tmp_path, [line("ipm", 10.0, 0.1),
                                  line("reluqp", 2.0, 0.5),
                                  line("reluqp", 2.05, 0.49)])
    assert rc == 0 and len(trend["rows"]) == 1
    assert trend["rows"][0]["key"]["solver"] == "reluqp"
    assert trend["rows"][0]["rate_verdict"] == "stable"
    # ... and a genuine reluqp regression still gates.
    rc, trend = _trend(tmp_path, [line("reluqp", 2.0, 0.5),
                                  line("reluqp", 1.0, 1.1)])
    assert rc == 1 and trend["n_regressions"] >= 1


# ------------------------------------------------- engine-level equivalence
def _mixed_cfg(n=64, pv=26, bat=6, pvb=6, horizon=4):
    cfg = default_config()
    cfg["community"]["total_number_homes"] = n
    cfg["community"]["homes_pv"] = pv
    cfg["community"]["homes_battery"] = bat
    cfg["community"]["homes_pv_battery"] = pvb
    cfg["home"]["hems"]["prediction_horizon"] = horizon
    cfg["home"]["hems"]["solver"] = "reluqp"
    return cfg


@pytest.fixture(scope="module")
def reluqp_parity_runs():
    """Superset vs bucketed chunk outputs for the reluqp family on the
    64-home mixed community (module-scoped: two engine compiles, asserted
    by several tests)."""
    from dragg_tpu.data import load_environment, load_waterdraw_profiles
    from dragg_tpu.engine import make_engine
    from dragg_tpu.homes import build_home_batch, create_homes

    cfg = _mixed_cfg()
    env = load_environment(cfg, data_dir=None)
    wd = load_waterdraw_profiles(None, seed=12)
    homes = create_homes(cfg, 24, 1, wd)
    batch = build_home_batch(homes, 4, 1,
                             int(cfg["home"]["hems"]["sub_subhourly_steps"]))
    cfg_sup = copy.deepcopy(cfg)
    cfg_sup["tpu"]["bucketed"] = "false"
    eng_sup = make_engine(batch, env, cfg_sup, 0)
    assert not eng_sup.bucketed and eng_sup.params.solver == "reluqp"
    eng_bkt = make_engine(batch, env, cfg, 0)   # auto → bucketed at 64
    assert eng_bkt.bucketed
    rps = np.zeros((3, eng_sup.params.horizon), np.float32)
    _, out_sup = eng_sup.run_chunk(eng_sup.init_state(), 0, rps)
    _, out_bkt = eng_bkt.run_chunk(eng_bkt.init_state(), 0, rps)
    return cfg, env, batch, eng_sup, eng_bkt, out_sup, out_bkt


def _assert_outputs_match_flip_aware(out_ref, out_cmp, cols, s):
    """The test_bucketed.py assertion set, tolerant of integer-rounding
    DEGENERACY: a home whose relaxed duty sits near .5 can legitimately
    round to different integer counts under different batch partitions
    (observed: ONE home's heat duty 4 vs 3 counts at t=1, swapping back
    at t=2 — the receding horizon compensates next step).  Such flip
    home-steps are bounded (≤ 2 % of home-steps, ≤ 1 count) and exempted
    from the tight per-home cost/state comparison; everything else —
    exact solvedness, aggregates, and the non-flip subset — holds at the
    shared tolerances.

    Continuous-state atols are looser than test_bucketed.py's (IPM)
    1e-3: a first-order ADMM iterate at eps_abs=eps_rel=1e-4 is only
    pinned to ~O(eps) — different compiled partitions legitimately stop
    at different points of the tolerance ball (observed max 2.2e-3 on
    ~20 °C indoor and 5.4e-3 on ~48 °C tank states — rel ~1.2e-4, the
    round-9 per-compile wobble scale), where the IPM polishes well
    inside 1e-3.  Temps get atol 1e-2 (0.01 °C — physically tight),
    battery leaves 5e-3."""
    from dragg_tpu.engine import OBS_FIELDS

    ref = {f: np.asarray(getattr(out_ref, f)) for f in out_ref._fields}
    cmp = {}
    for f in out_cmp._fields:
        if f in OBS_FIELDS:
            continue
        a = np.asarray(getattr(out_cmp, f))
        cmp[f] = a[:, cols] if a.ndim == 2 else a

    np.testing.assert_array_equal(cmp["correct_solve"],
                                  ref["correct_solve"])

    # Flip mask: home-steps where any applied duty count differs.
    flip = np.zeros(ref["cost"].shape, bool)
    exact = total = 0
    for key in ("hvac_cool_on", "hvac_heat_on", "wh_heat_on"):
        dc = np.abs(cmp[key] * s - ref[key] * s)
        assert np.max(dc) <= 1 + 1e-3, key       # never more than 1 count
        flip |= dc > 1e-3
        exact += int(np.sum(dc < 1e-3))
        total += dc.size
    assert exact / total >= 0.95, f"only {exact}/{total} actions match"
    assert flip.mean() <= 0.02, f"{flip.sum()} flip home-steps (> 2 %)"

    # Aggregates absorb the flips (±one count swaps across steps).
    np.testing.assert_allclose(cmp["agg_cost"], ref["agg_cost"],
                               rtol=1e-2, atol=5e-3)
    np.testing.assert_allclose(cmp["agg_load"], ref["agg_load"],
                               rtol=1e-2, atol=5e-3)

    nf = ~flip
    np.testing.assert_allclose(cmp["cost"][nf], ref["cost"][nf],
                               rtol=1e-2, atol=2e-3)
    np.testing.assert_allclose(cmp["temp_in"][nf], ref["temp_in"][nf],
                               atol=1e-2)
    np.testing.assert_allclose(cmp["temp_wh"][nf], ref["temp_wh"][nf],
                               atol=1e-2)
    np.testing.assert_allclose(cmp["e_batt"][nf], ref["e_batt"][nf],
                               atol=5e-3)
    np.testing.assert_allclose(cmp["p_batt_ch"][nf], ref["p_batt_ch"][nf],
                               atol=5e-3)
    np.testing.assert_allclose(cmp["p_batt_disch"][nf],
                               ref["p_batt_disch"][nf], atol=5e-3)
    # Flip home-steps: bounded by one duty count's worth of power/cost
    # and the one-step thermal effect of one count.
    if flip.any():
        assert np.max(np.abs(cmp["cost"][flip] - ref["cost"][flip])) < 0.5
        assert np.max(np.abs(cmp["temp_in"][flip]
                             - ref["temp_in"][flip])) < 1.0
        assert np.max(np.abs(cmp["temp_wh"][flip]
                             - ref["temp_wh"][flip])) < 1.0


@pytest.mark.slow
def test_reluqp_bucketed_matches_superset(reluqp_parity_runs):
    """Satellite: bucketed-vs-superset equivalence for the new family —
    each type bucket solves at its own shape with its own rho bank, and
    the merged outputs must reproduce the superset run (the
    test_bucketed.py assertion set, flip-aware — see
    _assert_outputs_match_flip_aware)."""
    _cfg, _env, _batch, eng_sup, eng_bkt, out_sup, out_bkt = \
        reluqp_parity_runs
    cols = eng_bkt.real_home_cols
    np.testing.assert_array_equal(cols, np.arange(64))
    _assert_outputs_match_flip_aware(out_sup, out_bkt, cols,
                                     eng_sup.params.s)
    # Healthy solve rates on both paths (not vacuous equivalence).
    assert float(np.asarray(out_sup.correct_solve).mean()) > 0.9
    assert float(np.max(np.asarray(out_bkt.r_prim_max))) < 1.0


@pytest.mark.slow
def test_reluqp_sharded_matches_single_device(reluqp_parity_runs):
    """Satellite: sharded-vs-single equivalence on the conftest 8-device
    CPU mesh — the ReLUQPCarry's (B, R, m, m) bank leaves shard over the
    homes axis like every other per-home tensor."""
    from dragg_tpu.parallel import make_mesh, make_sharded_engine

    cfg, env, batch, eng_sup, _eng_bkt, out_sup, _out_bkt = \
        reluqp_parity_runs
    sh = make_sharded_engine(batch, env, cfg, 0, mesh=make_mesh(8))
    assert sh.params.solver == "reluqp" and sh.bucketed
    for b in sh.bucket_info():
        assert b["n_slots"] % 8 == 0 and b["n_slots"] > 0
    rps = np.zeros((3, sh.params.horizon), np.float32)
    state = sh.init_state()
    assert "homes" in str(state[0].temp_in.sharding.spec)
    _, out_sh = sh.run_chunk(state, 0, rps)
    cols = sh.real_home_cols
    assert len(cols) == 64 and len(set(cols.tolist())) == 64
    _assert_outputs_match_flip_aware(out_sup, out_sh, cols, sh.params.s)


@pytest.mark.slow
def test_reluqp_compile_stall_names_stage(tmp_path):
    """Satellite chaos scenario: an injected hang inside a reluqp
    engine's XLA compile is stall-killed by the supervisor and the
    failure.COMPILE_HANG event names the stuck stage + the bucket
    pattern shapes (telemetry/compile_obs.py — the round-9 observatory
    applied to the round-10 family)."""
    from dragg_tpu import telemetry
    from dragg_tpu.resilience.supervisor import run_supervised

    telemetry.close_run()
    telemetry.init_run(str(tmp_path))
    child = (
        "import sys; sys.path.insert(0, %r)\n"
        "from dragg_tpu.resilience.heartbeat import beat\n"
        "beat({'stage': 'setup'})\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import numpy as np\n"
        "from dragg_tpu.config import default_config\n"
        "from dragg_tpu.data import load_environment, "
        "load_waterdraw_profiles\n"
        "from dragg_tpu.engine import make_engine\n"
        "from dragg_tpu.homes import build_home_batch, create_homes\n"
        "from dragg_tpu.telemetry.compile_obs import staged_compile\n"
        "cfg = default_config()\n"
        "cfg['community']['total_number_homes'] = 4\n"
        "cfg['community']['homes_pv'] = 0\n"
        "cfg['home']['hems']['prediction_horizon'] = 2\n"
        "cfg['home']['hems']['solver'] = 'reluqp'\n"
        "env = load_environment(cfg, data_dir=None)\n"
        "wd = load_waterdraw_profiles(None, seed=12)\n"
        "homes = create_homes(cfg, 24, 1, wd)\n"
        "batch = build_home_batch(homes, 2, 1, "
        "int(cfg['home']['hems']['sub_subhourly_steps']))\n"
        "engine = make_engine(batch, env, cfg, 0)\n"
        "rps = np.zeros((2, engine.params.horizon), np.float32)\n"
        "staged_compile(engine, engine.init_state(), 0, rps, "
        "label='reluqp-chaos')\n" % ROOT)
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["DRAGG_FAULT_INJECT"] = "hang@compile_compile"
    try:
        res = run_supervised([sys.executable, "-c", child],
                             deadline_s=600.0, stall_s=45.0,
                             label="reluqp-chaos", env=env)
    finally:
        telemetry.close_run()
    assert not res.ok and res.stalled
    recs = [json.loads(line)
            for line in open(tmp_path / telemetry.EVENTS_FILE)]
    fails = [r for r in recs if r["event"] == "failure.COMPILE_HANG"]
    assert fails, [r["event"] for r in recs]
    prog = fails[0]["progress"]
    assert prog["stage"] == "compile:compile"
    assert prog["label"] == "reluqp-chaos"
    assert "[" in prog["buckets"]  # "<type>[<slots>x<m_eq>]" shapes
