"""Scenario subsystem (ISSUE 10): EV / heat-pump home types + community
event timelines (tariff shocks, DR curtailment, outage islanding).

Parity conventions follow tests/test_qp_parity.py (objectives vs HiGHS on
identical matrices, never iterates) and tests/test_bucketed.py (bucketed
vs superset outputs mapped back to community order).  The byte-identity
test pins the acceptance invariant: an all-zero event timeline reproduces
the pre-scenario engine bit-for-bit with an unchanged compiled-pattern
count.
"""

import copy

import numpy as np
import pytest
from scipy.optimize import linprog

from dragg_tpu.config import default_config
from dragg_tpu.data import load_environment, load_waterdraw_profiles
from dragg_tpu.engine import make_engine
from dragg_tpu.fixtures import assemble_community_qp
from dragg_tpu.homes import build_home_batch, create_homes
from dragg_tpu.ops.admm import admm_solve_qp
from dragg_tpu.ops.qp import (
    HP_COP_MAX,
    HP_COP_MIN,
    QPLayout,
    SUPERSET_SPEC,
    TYPE_SPECS,
    densify_A,
    hp_cops,
    superset_spec_for,
)
from dragg_tpu.scenarios import (
    ScenarioError,
    apply_scenarios,
    build_timeline,
    empty_timeline,
    load_pack,
    pack_path,
    timeline_for,
)


def _mixed_cfg(n=18, pv=3, bat=3, pvb=3, ev=3, hp=3, horizon=3, seed=12,
               dt=1):
    cfg = default_config()
    cfg["community"]["total_number_homes"] = n
    cfg["community"]["homes_pv"] = pv
    cfg["community"]["homes_battery"] = bat
    cfg["community"]["homes_pv_battery"] = pvb
    cfg["community"]["homes_ev"] = ev
    cfg["community"]["homes_heat_pump"] = hp
    cfg["simulation"]["random_seed"] = seed
    cfg["agg"]["subhourly_steps"] = dt
    cfg["home"]["hems"]["prediction_horizon"] = horizon
    return cfg


def _engine_for(cfg, num_hours=48):
    dt = int(cfg["agg"]["subhourly_steps"])
    env = load_environment(cfg, data_dir=None)
    wd = load_waterdraw_profiles(None,
                                 seed=int(cfg["simulation"]["random_seed"]))
    homes = create_homes(cfg, num_hours * dt, dt, wd)
    h = int(cfg["home"]["hems"]["prediction_horizon"])
    batch = build_home_batch(
        homes, h * dt, dt, int(cfg["home"]["hems"]["sub_subhourly_steps"]))
    return make_engine(batch, env, cfg, 0), batch, env, homes


# ------------------------------------------------------------ spec/layout
def test_superset_spec_union():
    """EVERY legacy population unions to the historical superset (the
    floor — pre-scenario programs stay byte-for-byte, dead boxes
    included, even for all-base communities); scenario types widen it
    exactly by their blocks."""
    assert superset_spec_for(np.array([0, 1, 2, 3])) == SUPERSET_SPEC
    assert superset_spec_for(np.array([3])) == SUPERSET_SPEC  # all-base
    assert superset_spec_for(np.array([1, 3])) == SUPERSET_SPEC
    with_ev = np.array([0, 3, 4])
    s = superset_spec_for(with_ev)
    assert s.has_ev and not s.has_hp and s.has_batt and s.has_curt
    s = superset_spec_for(np.array([3, 5]))
    assert s.has_hp and not s.has_ev and s.has_batt  # floor keeps batt
    # has_grid is an ENGINE upgrade (event schedules), never a type's.
    assert not superset_spec_for(np.arange(6)).has_grid


def test_scenario_layout_blocks():
    """EV adds H charge columns + (H+1) SOC columns and H+1 rows; the grid
    block adds H columns + H rows; heat_pump changes no shapes at all."""
    H = 8
    base = QPLayout(H, TYPE_SPECS["base"])
    ev = QPLayout(H, TYPE_SPECS["ev"])
    hp = QPLayout(H, TYPE_SPECS["heat_pump"])
    assert (ev.n, ev.m_eq) == (base.n + 2 * H + 1, base.m_eq + H + 1)
    assert (hp.n, hp.m_eq) == (base.n, base.m_eq)
    grid = QPLayout(H, TYPE_SPECS["base"]._replace(has_grid=True))
    assert (grid.n, grid.m_eq) == (base.n + H, base.m_eq + H)
    assert ev.i_evch is not None and ev.i_eev is not None
    assert grid.i_pgr is not None and grid.r_pgr is not None


def test_hp_cop_band_matches_curve():
    """The assembled HVAC thermal coefficients of heat-pump homes equal
    a_in·P·COP(OAT) from the published curve, and resistive homes in the
    same batch keep the bit-identical base coefficients."""
    cfg = _mixed_cfg(n=6, pv=0, bat=0, pvb=0, ev=0, hp=3, horizon=4)
    eng, batch, env, _homes = _engine_for(cfg)
    lay, st = eng.layout, eng.static
    assert lay.has_hp and len(st.hp_cool_pos) == lay.H + 1
    state = eng.init_state()
    rps = np.zeros((1, eng.params.horizon), np.float32)
    eng.run_chunk(state, 0, rps)  # exercises the band in-trace
    # Rebuild the t=0 assembled values by hand.
    from dragg_tpu.ops.qp import assemble_qp_step

    H = lay.H
    n = eng.n_homes
    oat_w = np.asarray(eng._oat)[: H + 1]
    qp = assemble_qp_step(
        st, lay, eng.batch,
        oat_window=oat_w, ghi_window=np.asarray(eng._ghi)[: H + 1],
        price_total=np.zeros((n, H), np.float32),
        draw_frac=np.zeros((n, H + 1), np.float32),
        temp_in_init=np.asarray(batch.temp_in_init, np.float32),
        temp_wh_init=np.asarray(batch.temp_wh_init, np.float32),
        e_batt_init=np.zeros(n, np.float32),
        cool_cap=np.zeros(n, np.float32),
        heat_cap=np.full(n, 6.0, np.float32),
        wh_cap=6.0, discount=1.0)
    vals = np.asarray(qp.vals)
    # f64 recomputation (st.a_in is the engine's f32 copy — comparing
    # against it would round the wrong way).
    a_in = 3600.0 / (np.asarray(batch.hvac_c)
                     * int(cfg["agg"]["subhourly_steps"]))
    pc = np.asarray(batch.hvac_p_c)
    is_hp = np.asarray(batch.is_hp).astype(bool)
    cool_cop, _heat = hp_cops(oat_w[1:H + 1], batch.hp_cop_base,
                              batch.hp_cop_slope)
    cool_cop = np.asarray(cool_cop)
    assert np.all(cool_cop >= HP_COP_MIN) and np.all(cool_cop <= HP_COP_MAX)
    for k in range(H):
        got = vals[:, int(st.hp_cool_pos[k])]
        want = (a_in * pc * np.where(is_hp, cool_cop[:, k], 1.0)) \
            .astype(np.float32)
        np.testing.assert_allclose(got, want, rtol=1e-6)
    # Resistive homes' entries stay the exact base coefficient.
    np.testing.assert_array_equal(
        vals[~is_hp][:, int(st.hp_cool_pos[0])],
        (a_in * pc)[~is_hp].astype(np.float32))


# ----------------------------------------------------------- HiGHS parity
def test_ev_heat_pump_highs_objective_parity():
    """The new types' t=0 community QP solves to HiGHS' objective within
    the 1% budget (tests/test_qp_parity.py convention), home by home —
    EV SOC dynamics / deadline floors and COP-scaled thermal rows ride the
    same matrices HiGHS sees."""
    qp, pat, _lay, _s = assemble_community_qp(
        horizon_hours=4, n_homes=8, homes_pv=1, homes_battery=1,
        homes_pv_battery=1, homes_ev=2, homes_heat_pump=2)
    sol = admm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                        iters=4000, eps_abs=1e-4, eps_rel=1e-4)
    A = np.asarray(densify_A(pat, qp.vals), dtype=np.float64)
    beq = np.asarray(qp.b_eq, dtype=np.float64)
    l = np.asarray(qp.l_box, dtype=np.float64)
    u = np.asarray(qp.u_box, dtype=np.float64)
    q = np.asarray(qp.q, dtype=np.float64)
    x = np.asarray(sol.x, dtype=np.float64)
    solved = np.asarray(sol.solved)
    n_checked = 0
    for i in range(A.shape[0]):
        bounds = [(lo if np.isfinite(lo) else None,
                   hi if np.isfinite(hi) else None)
                  for lo, hi in zip(l[i], u[i])]
        ref = linprog(q[i], A_eq=A[i], b_eq=beq[i], bounds=bounds,
                      method="highs")
        if not ref.success:
            assert not solved[i], f"home {i}: HiGHS infeasible, we solved"
            continue
        assert solved[i], f"home {i}: HiGHS feasible but unsolved"
        gap = (float(q[i] @ x[i]) - float(ref.fun)) / max(abs(ref.fun), 1e-3)
        assert gap < 0.01, f"home {i}: cost gap {gap:.4%}"
        assert gap > -0.005, f"home {i}: beat the optimum — infeasible"
        n_checked += 1
    assert n_checked >= 6  # the mixed community must be mostly feasible


# ------------------------------------------------- bucketed / sharded legs
def _run_both(cfg, steps=3):
    cfg_b = copy.deepcopy(cfg)
    cfg_b["tpu"]["bucketed"] = "true"
    cfg_s = copy.deepcopy(cfg)
    cfg_s["tpu"]["bucketed"] = "false"
    eng_b, _batch, _env, _homes = _engine_for(cfg_b)
    eng_s, _batch2, _env2, _homes2 = _engine_for(cfg_s)
    assert eng_b.bucketed and not eng_s.bucketed
    rps = np.zeros((steps, eng_s.params.horizon), np.float32)
    _, out_b = eng_b.run_chunk(eng_b.init_state(), 0, rps)
    _, out_s = eng_s.run_chunk(eng_s.init_state(), 0, rps)
    return eng_b, eng_s, out_b, out_s


def _assert_parity(out_ref, out_new, cols, s):
    from dragg_tpu.engine import OBS_FIELDS

    ref = {f: np.asarray(getattr(out_ref, f)) for f in out_ref._fields}
    new = {}
    for f in out_new._fields:
        if f in OBS_FIELDS:
            continue
        a = np.asarray(getattr(out_new, f))
        new[f] = a[:, cols] if a.ndim == 2 else a
    np.testing.assert_array_equal(new["correct_solve"],
                                  ref["correct_solve"])
    np.testing.assert_allclose(new["cost"], ref["cost"], rtol=1e-2,
                               atol=2e-3)
    np.testing.assert_allclose(new["agg_cost"], ref["agg_cost"], rtol=1e-2,
                               atol=5e-3)
    for key in ("hvac_cool_on", "hvac_heat_on", "wh_heat_on"):
        counts_r = ref[key] * s
        counts_n = new[key] * s
        assert np.max(np.abs(counts_n - counts_r)) <= 1 + 1e-3, key
    np.testing.assert_allclose(new["temp_in"], ref["temp_in"], atol=1e-3)
    np.testing.assert_allclose(new["e_ev"], ref["e_ev"], atol=5e-3)
    np.testing.assert_allclose(new["p_ev_ch"], ref["p_ev_ch"], atol=5e-3)


def test_new_types_bucketed_matches_superset():
    """EV and heat_pump solve as their own bucket patterns with outputs
    matching the one-batch union-superset path (test_bucketed pattern)."""
    cfg = _mixed_cfg()
    eng_b, eng_s, out_b, out_s = _run_both(cfg)
    names = [b["name"] for b in eng_b.bucket_info()]
    assert "ev" in names and "heat_pump" in names
    # Type-specialized shapes: the ev bucket carries the SOC block, the
    # heat_pump bucket keeps the base shape.
    info = {b["name"]: b for b in eng_b.bucket_info()}
    H = eng_b.params.horizon
    assert info["ev"]["n_var"] == info["heat_pump"]["n_var"] + 2 * H + 1
    _assert_parity(out_s, out_b, eng_b.real_home_cols, eng_b.params.s)


@pytest.mark.slow
def test_new_types_sharded_8dev_matches(tmp_path):
    """The 8-device-mesh sharded leg for each new type: per-bucket shard
    padding on the conftest CPU mesh vs the single-device union-superset
    run (tests/test_bucketed.py::test_bucketed_sharded… pattern)."""
    from dragg_tpu.parallel import make_mesh, make_sharded_engine

    cfg = _mixed_cfg(n=24, pv=4, bat=4, pvb=4, ev=4, hp=4, horizon=3)
    cfg_s = copy.deepcopy(cfg)
    cfg_s["tpu"]["bucketed"] = "false"
    eng_s, _b, _e, _h = _engine_for(cfg_s)
    cfg_b = copy.deepcopy(cfg)
    cfg_b["tpu"]["bucketed"] = "true"
    dt = int(cfg_b["agg"]["subhourly_steps"])
    env = load_environment(cfg_b, data_dir=None)
    wd = load_waterdraw_profiles(None, seed=12)
    homes = create_homes(cfg_b, 48, dt, wd)
    batch = build_home_batch(homes, 3, dt, 6)
    sh = make_sharded_engine(batch, env, cfg_b, 0, mesh=make_mesh(8))
    assert sh.bucketed
    names = [b["name"] for b in sh.bucket_info()]
    assert "ev" in names and "heat_pump" in names
    for b in sh.bucket_info():
        assert b["n_slots"] % 8 == 0 and b["n_slots"] > 0
    rps = np.zeros((3, sh.params.horizon), np.float32)
    _, out_sh = sh.run_chunk(sh.init_state(), 0, rps)
    _, out_s = eng_s.run_chunk(eng_s.init_state(), 0, rps)
    _assert_parity(out_s, out_sh, sh.real_home_cols, sh.params.s)


# -------------------------------------------------------- event semantics
def test_all_zero_timeline_byte_identical():
    """THE acceptance invariant: an all-zero (inert) event timeline
    reproduces the pre-scenario engine byte-identically — same compiled
    pattern count, same shapes, bit-equal outputs."""
    cfg = _mixed_cfg(n=8, pv=2, bat=1, pvb=1, ev=0, hp=0, horizon=3)
    eng0, _b0, env, _h0 = _engine_for(cfg)
    inert = empty_timeline(1, len(np.asarray(env.oat)))
    assert inert.inert
    dt = int(cfg["agg"]["subhourly_steps"])
    wd = load_waterdraw_profiles(None, seed=12)
    homes = create_homes(cfg, 48, dt, wd)
    batch = build_home_batch(homes, 3, dt, 6)
    eng1 = make_engine(batch, env, cfg, 0, events=inert)
    assert eng1._events is None  # inert → the no-events fast path
    assert (eng1.layout.n, eng1.layout.m_eq) == (eng0.layout.n,
                                                 eng0.layout.m_eq)
    assert len(eng1.bucket_info()) == len(eng0.bucket_info())
    rps = np.zeros((3, eng0.params.horizon), np.float32)
    _, out0 = eng0.run_chunk(eng0.init_state(), 0, rps)
    _, out1 = eng1.run_chunk(eng1.init_state(), 0, rps)
    for f in out0._fields:
        np.testing.assert_array_equal(np.asarray(getattr(out0, f)),
                                      np.asarray(getattr(out1, f)),
                                      err_msg=f)


def test_tariff_shock_raises_cost_and_warns():
    """A tariff shock flows into the assembled prices (higher step cost at
    equal load), and scheduling one against the bug-parity TOU ladder
    warns (the fix_tou_peak satellite)."""
    cfg = _mixed_cfg(n=6, pv=1, bat=1, pvb=1, ev=0, hp=0, horizon=3)
    cfg["tpu"]["fix_tou_peak"] = True  # the intended ladder — no warning
    cfg_shock = copy.deepcopy(cfg)
    cfg_shock["scenarios"]["events"] = [dict(
        kind="tariff_shock", start_hour=0, duration_hours=48,
        price_delta=0.25)]
    eng0, _b, _e, _h = _engine_for(cfg)
    eng1, _b1, _e1, _h1 = _engine_for(cfg_shock)
    assert eng1._events is not None and eng1._events.has_price
    # Same shapes — a price shock is data, not structure.
    assert (eng1.layout.n, eng1.layout.m_eq) == (eng0.layout.n,
                                                 eng0.layout.m_eq)
    rps = np.zeros((3, eng0.params.horizon), np.float32)
    _, out0 = eng0.run_chunk(eng0.init_state(), 0, rps)
    _, out1 = eng1.run_chunk(eng1.init_state(), 0, rps)
    load0 = np.asarray(out0.agg_load).sum()
    assert np.asarray(out1.agg_cost).sum() > np.asarray(out0.agg_cost).sum()
    assert load0 > 0  # winter heating: the community draws power
    # The warning leg: same schedule on the bug-parity ladder.
    cfg_bug = copy.deepcopy(cfg_shock)
    cfg_bug["tpu"]["fix_tou_peak"] = False
    with pytest.warns(UserWarning, match="fix_tou_peak"):
        timeline_for(cfg_bug, 1, 100, 1, 0)
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")
        timeline_for(cfg_shock, 1, 100, 1, 0)  # fixed ladder: no warning


def test_dr_cap_enforced_on_solved_homes():
    """During a DR window, solved homes obey the tightened p_grid cap (the
    explicit grid block's per-step box)."""
    cfg = _mixed_cfg(n=8, pv=2, bat=2, pvb=2, ev=0, hp=0, horizon=3)
    cfg["scenarios"]["events"] = [dict(
        kind="dr", start_hour=0, duration_hours=48, p_cap_kw=2.5,
        comfort_relax_degc=2.0)]
    # The PLAN obeys the cap exactly; the integer-pinned APPLIED action
    # can overshoot by up to one duty count per appliance (rounding —
    # docs/scenarios.md), so the exact-cap leg pins the relaxation.
    cfg["tpu"]["integer_first_action"] = False
    eng, batch, _e, _h = _engine_for(cfg)
    assert eng.layout.has_grid
    rps = np.zeros((4, eng.params.horizon), np.float32)
    _, outs = eng.run_chunk(eng.init_state(), 0, rps)
    solved = np.asarray(outs.correct_solve) > 0
    pg = np.asarray(outs.p_grid)
    assert solved.any()
    assert np.all(pg[solved] <= 2.5 + 0.05), float(pg[solved].max())
    # Integer-action leg: overshoot bounded by one duty count/appliance.
    cfg_i = copy.deepcopy(cfg)
    cfg_i["tpu"]["integer_first_action"] = True
    eng_i, _b, _e2, _h2 = _engine_for(cfg_i)
    _, outs_i = eng_i.run_chunk(eng_i.init_state(), 0, rps)
    solved_i = np.asarray(outs_i.correct_solve) > 0
    # One duty count per appliance = its per-substep power (batch units).
    slack = float((np.asarray(batch.hvac_p_c) + np.asarray(batch.hvac_p_h)
                   + np.asarray(batch.wh_p)).max())
    assert np.all(np.asarray(outs_i.p_grid)[solved_i] <= 2.5 + slack + 0.05)


def test_outage_islands_solved_homes():
    """During an outage window, solved homes' applied grid power is ZERO —
    battery/PV homes ride through islanded, all-electric homes route to
    the fallback (by design; docs/scenarios.md)."""
    cfg = _mixed_cfg(n=6, pv=0, bat=0, pvb=6, ev=0, hp=0, horizon=3)
    cfg["scenarios"]["events"] = [dict(
        kind="outage", start_hour=1, duration_hours=2,
        comfort_relax_degc=3.0)]
    # Exact islanding is a property of the PLAN — integer duty pinning
    # rounds the applied action within one count (docs/scenarios.md).
    cfg["tpu"]["integer_first_action"] = False
    eng, _b, _e, _h = _engine_for(cfg)
    rps = np.zeros((4, eng.params.horizon), np.float32)
    _, outs = eng.run_chunk(eng.init_state(), 0, rps)
    solved = np.asarray(outs.correct_solve) > 0
    pg = np.asarray(outs.p_grid)
    out_steps = [1, 2]  # dt=1: sim steps inside the outage window
    assert solved[out_steps].any(), "no pv_battery home rode the island"
    island = np.abs(pg[out_steps][solved[out_steps]])
    assert np.all(island <= 0.05), float(island.max())


def test_ev_daily_cycle():
    """EV semantics over one simulated day: no charging while away, SOC
    within [0, cap], the return-trip drain lands at the return step, and
    homes that can reach their target before departure do."""
    cfg = _mixed_cfg(n=4, pv=0, bat=0, pvb=0, ev=4, hp=0, horizon=6,
                     seed=3)
    eng, batch, _env, _homes = _engine_for(cfg, num_hours=48)
    rps = np.zeros((24, eng.params.horizon), np.float32)
    _, outs = eng.run_chunk(eng.init_state(), 0, rps)
    solved = np.asarray(outs.correct_solve) > 0
    p_ev = np.asarray(outs.p_ev_ch)
    e_ev = np.asarray(outs.e_ev)
    a_s = np.asarray(batch.ev_away_start)
    a_e = np.asarray(batch.ev_away_end)
    cap = np.asarray(batch.ev_cap)
    target = np.asarray(batch.ev_target_kwh)
    rate = np.asarray(batch.ev_rate)
    eff = np.asarray(batch.ev_ch_eff)
    init = np.asarray(batch.ev_init_frac) * cap
    trip = np.asarray(batch.ev_trip_kwh)
    hours = np.arange(24)
    away = (hours[:, None] >= a_s[None]) & (hours[:, None] < a_e[None])
    # Availability: zero charge during away hours (solved or fallback).
    assert np.all(p_ev[away] <= 1e-4)
    assert np.all(e_ev >= -1e-4) and np.all(e_ev <= cap[None] + 1e-3)
    for i in range(4):
        dep = int(np.ceil(a_s[i]))   # first away hour
        ret = int(np.ceil(a_e[i]))   # first home hour
        # Return-trip drain: SOC drops by trip_kwh across the last away
        # step (no charging is possible there).
        drop = e_ev[ret - 2, i] - e_ev[ret - 1, i]
        np.testing.assert_allclose(drop, min(trip[i], e_ev[ret - 2, i]),
                                   atol=5e-3)
        # Deadline: if the pre-departure hours give enough charge
        # capacity AND every pre-departure step solved, the SOC at
        # departure holds the target.
        reach = init[i] + dep * rate[i] * eff[i]
        if reach >= target[i] and solved[:dep, i].all():
            assert e_ev[dep - 1, i] >= target[i] - 5e-2, (
                i, e_ev[:, i], target[i])


def test_fleet_per_community_event_schedules():
    """Events key per community: a 2-community fleet with a DR window on
    community 1 only caps community 1's homes and leaves community 0's
    program untouched (same compiled pattern count as the fleet without
    events, +grid block)."""
    from dragg_tpu.homes import build_fleet_batch, create_fleet_homes

    cfg = _mixed_cfg(n=8, pv=2, bat=0, pvb=2, ev=2, hp=2, horizon=3)
    cfg["fleet"]["communities"] = 2
    cfg["scenarios"]["events"] = [dict(
        kind="outage", start_hour=1, duration_hours=3, communities=[1],
        comfort_relax_degc=3.0)]
    dt = 1
    env = load_environment(cfg, data_dir=None)
    wd = load_waterdraw_profiles(None, seed=12)
    homes = create_fleet_homes(cfg, 48, dt, wd)
    batch, fleet = build_fleet_batch(homes, cfg, 3, dt, 6)
    eng = make_engine(batch, env, cfg, 0, fleet=fleet)
    assert eng._events is not None and eng._events.n_communities == 2
    rps = np.zeros((3, eng.params.horizon), np.float32)
    _, outs = eng.run_chunk(eng.init_state(), 0, rps)
    pairs = eng.real_home_pairs
    pg = np.asarray(outs.p_grid)
    solved = np.asarray(outs.correct_solve) > 0
    c1 = pairs[pairs[:, 0] == 1][:, 1]
    # Community 1's solved homes are islanded at the outage steps…
    island = pg[1:3][:, c1][solved[1:3][:, c1]]
    assert np.all(np.abs(island) <= 0.05)
    # …while community 0 keeps drawing grid power.
    c0 = pairs[pairs[:, 0] == 0][:, 1]
    assert np.abs(pg[1:3][:, c0]).max() > 0.1


# ----------------------------------------------------- packs and timeline
def test_timeline_builder_semantics():
    ev_dr = dict(kind="dr", start_hour=2, duration_hours=2, p_cap_kw=3.0,
                 comfort_relax_degc=1.0)
    ev_out = dict(kind="outage", start_hour=3, duration_hours=2,
                  comfort_relax_degc=2.0)
    tl = build_timeline([ev_dr, ev_out], 1, 10, 1, 0)
    # Overlap composes as the tightest cap; outage also floors exports.
    assert tl.cap[0, 2] == 3.0 and tl.cap[0, 3] == 0.0 and tl.cap[0, 4] == 0
    assert np.isinf(tl.cap[0, 1]) and np.isinf(tl.cap[0, 5])
    assert tl.floor[0, 3] == 0.0 and np.isneginf(tl.floor[0, 2])
    assert tl.relax[0, 3] == 2.0 and tl.relax[0, 2] == 1.0
    # Horizon-edge clipping: a window running past the series end clips.
    tl2 = build_timeline([dict(kind="dr", start_hour=8, duration_hours=10,
                               p_cap_kw=1.0)], 1, 10, 1, 0)
    assert tl2.cap[0, 9] == 1.0 and tl2.cap[0, 7] > 1.0
    # Daily repetition.
    tl3 = build_timeline([dict(kind="tariff_shock", start_hour=1,
                               duration_hours=1, repeat_hours=24,
                               price_delta=0.1)], 1, 72, 1, 0)
    assert tl3.price[0, 1] > 0 and tl3.price[0, 25] > 0 \
        and tl3.price[0, 49] > 0 and tl3.price[0, 2] == 0
    # Inert schedules collapse to None.
    assert build_timeline([], 1, 10, 1, 0) is None
    assert build_timeline([dict(kind="tariff_shock", start_hour=0,
                                duration_hours=1, price_delta=0.0)],
                          1, 10, 1, 0) is None


def test_timeline_validation_errors():
    with pytest.raises(ScenarioError, match="kind"):
        build_timeline([dict(kind="nope", start_hour=0, duration_hours=1)],
                       1, 10, 1, 0)
    with pytest.raises(ScenarioError, match="duration"):
        build_timeline([dict(kind="dr", start_hour=0, duration_hours=0,
                             p_cap_kw=1.0)], 1, 10, 1, 0)
    with pytest.raises(ScenarioError, match="repeat_hours"):
        build_timeline([dict(kind="dr", start_hour=0, duration_hours=4,
                             repeat_hours=2, p_cap_kw=1.0)], 1, 10, 1, 0)
    with pytest.raises(ScenarioError, match="communities"):
        build_timeline([dict(kind="dr", start_hour=0, duration_hours=1,
                             p_cap_kw=1.0, communities=[3])], 2, 10, 1, 0)


def test_shipped_pack_loads_and_expands():
    """data/packs/stress_dr_outage.toml parses, its mix expands into the
    community counts, and its events reach the engine timeline."""
    path = pack_path("stress_dr_outage")
    pack = load_pack(path)
    assert pack["meta"]["name"] == "stress_dr_outage"
    assert {e["kind"] for e in pack["events"]} == {"tariff_shock", "dr",
                                                   "outage"}
    cfg = default_config()
    cfg["community"]["total_number_homes"] = 40
    cfg["tpu"]["fix_tou_peak"] = True
    cfg["scenarios"]["pack"] = "stress_dr_outage"
    cfg2 = apply_scenarios(cfg)
    assert cfg2["community"]["homes_ev"] == 4
    assert cfg2["community"]["homes_heat_pump"] == 4
    assert cfg2["community"]["homes_pv"] == 12
    assert len(cfg2["scenarios"]["events"]) == 3
    # Idempotent: a second application changes nothing.
    assert apply_scenarios(cfg2) == cfg2
    tl = timeline_for(cfg2, 1, 24 * 7, 1, 0)
    assert tl is not None and tl.has_price and tl.has_grid and tl.has_relax
    # An UNEXPANDED pack is never half-applied: the timeline ignores it
    # with a loud warning (its [mix] never reached home synthesis, so
    # running its schedule would target a population it didn't declare).
    with pytest.warns(UserWarning, match="never expanded"):
        tl2 = timeline_for(cfg, 1, 24 * 7, 1, 0)
    assert tl2 is None


def test_pack_errors():
    with pytest.raises(ScenarioError, match="not found"):
        pack_path("no_such_pack")
    cfg = default_config()
    cfg["scenarios"]["pack"] = "no_such_pack"
    with pytest.raises(ScenarioError):
        apply_scenarios(cfg)


def test_fix_tou_peak_ladder():
    """The fix_tou_peak satellite: the reference bug (peak overwritten by
    shoulder — dragg/aggregator.py:214-215) is reproduced by default and
    fixed behind the flag; the peak tier only ever applies when fixed."""
    from datetime import datetime

    from dragg_tpu.data import build_tou

    start = datetime(2015, 1, 1, 0)
    bug = build_tou(48, start, 1, 0.07, tou_enabled=True,
                    fix_tou_peak=False)
    fixed = build_tou(48, start, 1, 0.07, tou_enabled=True,
                      fix_tou_peak=True)
    # Bug parity: the whole shoulder window (peak hours included) reads
    # the shoulder price; the peak price appears nowhere.
    assert np.all(bug[9:21] == 0.09) and not np.any(bug == 0.13)
    # Fixed: peak tier inside the shoulder window.
    assert np.all(fixed[14:18] == 0.13)
    assert np.all(fixed[9:14] == 0.09) and np.all(fixed[18:21] == 0.09)
    assert np.all(fixed[:9] == 0.07) and np.all(fixed[21:24] == 0.07)
