"""Multi-host (multi-process) tests run as N local CPU processes.

The reference scales across hosts by launching one pathos process per home
against a shared Redis (dragg/aggregator.py:723-724); here the equivalent is
one JAX program spanning processes (deploy/launch_tpu_pod.sh +
``DRAGG_DISTRIBUTED=1``).  These tests exercise that path for real — two
OS processes, gloo CPU collectives, a device mesh spanning both — covering:

* the ``python -m dragg_tpu run`` multi-host init path (VERDICT r2 #6);
* per-process shard checkpoints + broadcast-coordinated resume on
  SEPARATE outputs directories, i.e. the non-shared-filesystem pod case
  (VERDICT r2 #7, ADVICE r2 aggregator.try_resume finding).

Each subprocess gets its own coordinator port (OS-assigned, freed just
before use) and 2 virtual CPU devices, so the global mesh is 4-wide.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _toml_dump(d: dict) -> str:
    """Minimal TOML writer for the config dict (flat scalar/list values in
    nested tables — all default_config ever contains)."""

    def fmt(v):
        if isinstance(v, bool):
            return "true" if v else "false"
        if isinstance(v, (int, float)):
            return repr(v)
        if isinstance(v, str):
            return json.dumps(v)
        if isinstance(v, list):
            return "[" + ", ".join(fmt(x) for x in v) + "]"
        raise TypeError(f"cannot TOML-serialize {type(v).__name__}")

    lines: list[str] = []

    def walk(table: dict, prefix: str) -> None:
        scalars = {k: v for k, v in table.items() if not isinstance(v, dict)}
        subs = {k: v for k, v in table.items() if isinstance(v, dict)}
        if prefix and scalars:
            lines.append(f"[{prefix}]")
        for k, v in scalars.items():
            lines.append(f"{k} = {fmt(v)}")
        for k, v in subs.items():
            walk(v, f"{prefix}.{k}" if prefix else k)

    walk(d, "")
    return "\n".join(lines) + "\n"


def _tiny_cfg_dict(days: int = 1, resume: bool = False) -> dict:
    from dragg_tpu.config import default_config

    cfg = default_config()
    cfg["community"]["total_number_homes"] = 4
    cfg["community"]["homes_pv"] = 1
    cfg["community"]["homes_battery"] = 1
    cfg["community"]["homes_pv_battery"] = 1
    cfg["simulation"]["start_datetime"] = "2015-01-01 00"
    cfg["simulation"]["end_datetime"] = f"2015-01-0{1 + days} 00"
    cfg["simulation"]["checkpoint_interval"] = "daily"
    cfg["simulation"]["resume"] = resume
    cfg["home"]["hems"]["prediction_horizon"] = 2
    cfg["tpu"]["admm_iters"] = 200
    return cfg


def _launch_pair(cmd_for, env_extra, timeout=600):
    """Run process 0 and 1 concurrently; return their CompletedProcess-like
    (rc, out) pairs.  ``cmd_for(pid)`` builds each argv."""
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)  # axon plugin hooks interpreter start
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "DRAGG_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "DRAGG_NUM_PROCESSES": "2",
            "DRAGG_PROCESS_ID": str(pid),
        })
        env.update(env_extra)
        procs.append(subprocess.Popen(
            cmd_for(pid), env=env, cwd=ROOT,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out))
    return outs


def test_distributed_run_entry_two_process(tmp_path):
    """`python -m dragg_tpu run` with DRAGG_DISTRIBUTED=1 as two CPU
    processes: the real multi-host entry (deploy/launch_tpu_pod.sh:48-60)
    initializes, runs one simulated day over the 4-device global mesh, and
    only process 0 writes results."""
    from dragg_tpu.config import default_config  # noqa: F401 — import check

    cfg = _tiny_cfg_dict(days=1)
    cfg_path = str(tmp_path / "config.toml")
    with open(cfg_path, "w") as f:
        f.write(_toml_dump(cfg))
    outs_dir = {pid: str(tmp_path / f"host{pid}") for pid in range(2)}

    results = _launch_pair(
        lambda pid: [sys.executable, "-m", "dragg_tpu", "run",
                     "--config", cfg_path, "--outputs-dir", outs_dir[pid]],
        env_extra={"DRAGG_DISTRIBUTED": "1"},
    )
    for pid, (rc, out) in enumerate(results):
        assert rc == 0, f"process {pid} failed:\n{out[-4000:]}"

    # Rank 0 wrote the full-length results; rank 1's "disk" has none
    # (write_outputs is rank-0-gated — aggregator.py).
    found = []
    for root, _, files in os.walk(outs_dir[0]):
        if "results.json" in files:
            found.append(os.path.join(root, "results.json"))
    assert found, "process 0 wrote no results.json"
    res = json.load(open(found[0]))
    a_home = next(n for n in res if n != "Summary")
    assert len(res[a_home]["p_grid_opt"]) == 24
    for root, _, files in os.walk(outs_dir[1]):
        assert "results.json" not in files


_DRIVER = textwrap.dedent("""
    import json, os, sys
    import jax
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(os.environ["DRAGG_COORDINATOR_ADDRESS"],
                               int(os.environ["DRAGG_NUM_PROCESSES"]),
                               int(os.environ["DRAGG_PROCESS_ID"]))
    sys.path.insert(0, {root!r})
    sys.path.insert(0, os.path.join({root!r}, "tests"))
    from test_distributed import _tiny_cfg_dict
    from dragg_tpu.aggregator import Aggregator

    mode = sys.argv[1]            # full | partial | resume | rl
    outputs_dir = sys.argv[2]
    days = 1 if mode == "rl" else 2
    cfg = _tiny_cfg_dict(days=days, resume=(mode == "resume"))
    if mode == "rl":
        cfg["simulation"]["run_rbo_mpc"] = False
        cfg["simulation"]["run_rl_agg"] = True
    agg = Aggregator(cfg, data_dir=None, outputs_dir=outputs_dir)
    if mode == "partial":
        agg.stop_after_chunks = 1
    agg.run()
    print("DRIVER_DONE", mode, "resumed_from", agg.resumed_from, flush=True)
""")


@pytest.mark.slow  # round-11 tier-1 budget trim: the run-entry two-process test keeps the multi-process init covered; the rl_agg variant re-runs it with RL on top
def test_distributed_rl_agg_two_process(tmp_path):
    """The RL-aggregator run mode (fused agent + community scan) over two
    processes: the chunk jit takes the engine constants as arguments
    (rl/runner.py) and the agent/env carries replicate on the global
    mesh — this is the one multi-host code path the baseline tests don't
    touch."""
    driver = str(tmp_path / "driver.py")
    with open(driver, "w") as f:
        f.write(_DRIVER.format(root=ROOT))
    dirs = {pid: str(tmp_path / f"host{pid}") for pid in range(2)}
    results = _launch_pair(
        lambda pid: [sys.executable, driver, "rl", dirs[pid]], env_extra={})
    for pid, (rc, out) in enumerate(results):
        assert rc == 0, f"rl process {pid} failed:\n{out[-4000:]}"
    found = telemetry = False
    for root, _, files in os.walk(dirs[0]):
        if "results.json" in files and os.path.basename(root) == "rl_agg":
            res = json.load(open(os.path.join(root, "results.json")))
            assert len(res["Summary"]["RP"]) == 24
            found = True
        if "utility_agent-results.json" in files:
            rl = json.load(open(os.path.join(root, "utility_agent-results.json")))
            assert len(rl["reward"]) == 24
            telemetry = True
    assert found, "rank 0 wrote no rl_agg results.json"
    assert telemetry, "rank 0 wrote no agent telemetry (write_rl_data)"
    for root, _, files in os.walk(dirs[1]):
        assert "utility_agent-results.json" not in files, \
            "non-zero rank wrote agent telemetry"


@pytest.mark.slow  # round-11 tier-1 budget trim: tier-1 keeps the two lighter 2-process entry tests (run entry, rl_agg); the bit-exact resume A/B runs four supervised child processes
def test_distributed_checkpoint_resume_bit_exact(tmp_path):
    """Non-shared-FS pod resume: two processes checkpoint to SEPARATE
    outputs directories (each holding only its own state shard), the run is
    interrupted, and the resumed 2-process run reproduces the uninterrupted
    2-process run's results bit-exactly."""
    driver = str(tmp_path / "driver.py")
    with open(driver, "w") as f:
        f.write(_DRIVER.format(root=ROOT))

    def run_mode(mode, base):
        dirs = {pid: str(tmp_path / base / f"host{pid}") for pid in range(2)}
        results = _launch_pair(
            lambda pid: [sys.executable, driver, mode, dirs[pid]],
            env_extra={})
        for pid, (rc, out) in enumerate(results):
            assert rc == 0, f"{mode} process {pid} failed:\n{out[-4000:]}"
            assert "DRIVER_DONE" in out
        return dirs, results

    # Uninterrupted 2-process reference.
    full_dirs, _ = run_mode("full", "full")

    def results_json(dirs):
        for root, _, files in os.walk(dirs[0]):
            if "results.json" in files:
                return json.load(open(os.path.join(root, "results.json")))
        raise AssertionError("no results.json under " + dirs[0])

    expected = results_json(full_dirs)

    # Interrupted run in fresh directories, then resume in the SAME ones.
    part_dirs, _ = run_mode("partial", "resumed")
    # Both hosts hold their own shard of the checkpoint; host1 has no
    # progress.json (rank-0-only) — exactly the non-shared-FS layout.
    ck0 = ck1 = None
    for pid, d in part_dirs.items():
        for root, _, files in os.walk(d):
            for fn in files:
                if fn.startswith("state.proc"):
                    if pid == 0:
                        ck0 = os.path.join(root, fn)
                    else:
                        ck1 = os.path.join(root, fn)
    assert ck0 and "proc00000-of-00002" in ck0
    assert ck1 and "proc00001-of-00002" in ck1

    _, resume_results = run_mode("resume", "resumed")
    assert any("resumed_from" in out and "ckpt_t" in out
               for _, out in resume_results), \
        "resume run did not actually resume from a checkpoint"
    got = results_json(part_dirs)

    for name in expected:
        if name == "Summary":
            continue
        for key, vals in expected[name].items():
            if isinstance(vals, list):
                np.testing.assert_array_equal(
                    np.asarray(vals), np.asarray(got[name][key]),
                    err_msg=f"{name}.{key} diverged across distributed resume")
    np.testing.assert_array_equal(
        np.asarray(expected["Summary"]["p_grid_aggregate"]),
        np.asarray(got["Summary"]["p_grid_aggregate"]))
