"""Real-shape sharding coverage (VERDICT r5 next-8): the multichip
dryrun and the parallel tests run tiny smoke shapes, so a
shape-dependent sharding bug (padding arithmetic, per-shard VMEM/block
choices, collective layouts that only materialize at scale) could hide
until an on-chip window.  This runs ONE 10k-home × 24h-horizon sharded
chunk on the 8-device virtual CPU mesh — the BASELINE row-3 shape the
headline bench measures.

Slow-marked: ~3-6 min on a 2-core CPU host; tier-1 (`-m 'not slow'`)
skips it, CI's slow lane and the pre-window checklist run it.
"""

import numpy as np
import pytest

import jax


@pytest.mark.slow
def test_10k_24h_sharded_chunk_on_virtual_mesh():
    from dragg_tpu.config import default_config
    from dragg_tpu.data import load_environment, load_waterdraw_profiles, waterdraw_path
    from dragg_tpu.homes import build_home_batch, create_homes
    from dragg_tpu.parallel.mesh import make_sharded_engine

    assert len(jax.devices()) == 8, "conftest pins the 8-device CPU mesh"

    n = 10_000
    cfg = default_config()
    cfg["community"]["total_number_homes"] = n
    cfg["community"]["homes_pv"] = int(0.4 * n)
    cfg["community"]["homes_battery"] = int(0.1 * n)
    cfg["community"]["homes_pv_battery"] = int(0.1 * n)
    cfg["home"]["hems"]["prediction_horizon"] = 24
    cfg["home"]["hems"]["solver"] = "ipm"

    env = load_environment(cfg)
    dt = int(cfg["agg"]["subhourly_steps"])
    wd = load_waterdraw_profiles(waterdraw_path(cfg, None), seed=12)
    homes = create_homes(cfg, 24 * dt, dt, wd)
    batch = build_home_batch(homes, 24 * dt, dt,
                             int(cfg["home"]["hems"]["sub_subhourly_steps"]))
    eng = make_sharded_engine(batch, env, cfg, 0)
    assert eng.n_homes % 8 == 0 and eng.true_n_homes == n

    state = eng.init_state()
    rps = np.zeros((2, eng.params.horizon), dtype=np.float32)
    state, outs = eng.run_chunk(state, 0, rps)
    jax.block_until_ready(outs.agg_load)

    solved = np.asarray(outs.correct_solve)[:, :n]
    assert solved.shape == (2, n)
    # Bundled-data day-1 solve rate is ~1.0 at this shape
    # (docs/forensics_10k_bundled_r5.json); anything below 0.95 in a
    # 2-step chunk is a sharding/shape regression, not weather.
    assert float(solved.mean()) >= 0.95
    for leaf, name in zip(outs, outs._fields):
        assert np.all(np.isfinite(np.asarray(leaf))), f"non-finite {name}"
    # Aggregates mask the padded replica homes: the community load must
    # equal the per-home sum over REAL homes only.
    agg = np.asarray(outs.agg_load)
    per_home = np.asarray(outs.p_grid)[:, :n].sum(axis=1)
    np.testing.assert_allclose(agg, per_home, rtol=2e-4)
