"""Real-shape sharding coverage (VERDICT r5 next-8): the multichip
dryrun and the parallel tests run tiny smoke shapes, so a
shape-dependent sharding bug (padding arithmetic, per-shard VMEM/block
choices, collective layouts that only materialize at scale) could hide
until an on-chip window.  This runs ONE 10k-home × 24h-horizon sharded
chunk on the 8-device virtual CPU mesh — the BASELINE row-3 shape the
headline bench measures.

Slow-marked: ~3-6 min on a 2-core CPU host; tier-1 (`-m 'not slow'`)
skips it, CI's slow lane and the pre-window checklist run it.
"""

import numpy as np
import pytest

import jax


@pytest.mark.slow
def test_10k_24h_sharded_chunk_on_virtual_mesh():
    from dragg_tpu.config import default_config
    from dragg_tpu.data import load_environment, load_waterdraw_profiles, waterdraw_path
    from dragg_tpu.homes import build_home_batch, create_homes
    from dragg_tpu.parallel.mesh import make_sharded_engine

    assert len(jax.devices()) == 8, "conftest pins the 8-device CPU mesh"

    n = 10_000
    cfg = default_config()
    cfg["community"]["total_number_homes"] = n
    cfg["community"]["homes_pv"] = int(0.4 * n)
    cfg["community"]["homes_battery"] = int(0.1 * n)
    cfg["community"]["homes_pv_battery"] = int(0.1 * n)
    cfg["home"]["hems"]["prediction_horizon"] = 24
    cfg["home"]["hems"]["solver"] = "ipm"

    env = load_environment(cfg)
    dt = int(cfg["agg"]["subhourly_steps"])
    wd = load_waterdraw_profiles(waterdraw_path(cfg, None), seed=12)
    homes = create_homes(cfg, 24 * dt, dt, wd)
    batch = build_home_batch(homes, 24 * dt, dt,
                             int(cfg["home"]["hems"]["sub_subhourly_steps"]))
    eng = make_sharded_engine(batch, env, cfg, 0)
    assert eng.n_homes % 8 == 0 and eng.true_n_homes == n

    state = eng.init_state()
    rps = np.zeros((2, eng.params.horizon), dtype=np.float32)
    state, outs = eng.run_chunk(state, 0, rps)
    jax.block_until_ready(outs.agg_load)

    # real_home_cols is the authoritative slot→community mapping (a
    # bucketed engine interleaves pad slots at bucket boundaries — the
    # 10k bench-mix buckets happen to divide 8 evenly today, but a prefix
    # slice would silently misattribute homes the day that changes).
    cols = eng.real_home_cols
    solved = np.asarray(outs.correct_solve)[:, cols]
    assert solved.shape == (2, n)
    # Bundled-data day-1 solve rate is ~1.0 at this shape
    # (docs/forensics_10k_bundled_r5.json); anything below 0.95 in a
    # 2-step chunk is a sharding/shape regression, not weather.
    assert float(solved.mean()) >= 0.95
    for leaf, name in zip(outs, outs._fields):
        assert np.all(np.isfinite(np.asarray(leaf))), f"non-finite {name}"
    # Aggregates mask the padded replica homes: the community load must
    # equal the per-home sum over REAL homes only.
    agg = np.asarray(outs.agg_load)
    per_home = np.asarray(outs.p_grid)[:, cols].sum(axis=1)
    np.testing.assert_allclose(agg, per_home, rtol=2e-4)


@pytest.mark.slow
def test_fleet_10k_24h_sharded_chunk_on_virtual_mesh():
    """The community-axis leg of the real-shape dryrun (ISSUE 8 raising
    VERDICT r5 next-8 again): 4 communities × 2.5k homes folded into one
    10k-home fleet batch, sharded over the 8-device virtual mesh — the
    type buckets hold C·B_type homes, per-bucket shard padding interacts
    with the fleet's type-major order, and the community-major output
    mapping is exercised at the headline shape rather than smoke shapes.
    Pattern count must stay the single-community bucket set (compile
    flat in C)."""
    from dragg_tpu.config import default_config
    from dragg_tpu.data import load_environment, load_waterdraw_profiles
    from dragg_tpu.homes import build_fleet_batch, create_fleet_homes
    from dragg_tpu.parallel.mesh import make_sharded_engine

    assert len(jax.devices()) == 8, "conftest pins the 8-device CPU mesh"

    n, C = 2500, 4
    cfg = default_config()
    cfg["community"]["total_number_homes"] = n
    cfg["community"]["homes_pv"] = int(0.4 * n)
    cfg["community"]["homes_battery"] = int(0.1 * n)
    cfg["community"]["homes_pv_battery"] = int(0.1 * n)
    cfg["home"]["hems"]["prediction_horizon"] = 24
    cfg["home"]["hems"]["solver"] = "ipm"
    cfg["fleet"]["communities"] = C
    cfg["fleet"]["seed_stride"] = 3

    env = load_environment(cfg)
    dt = int(cfg["agg"]["subhourly_steps"])
    from dragg_tpu.data import waterdraw_path

    wd = load_waterdraw_profiles(waterdraw_path(cfg, None), seed=12)
    homes = create_fleet_homes(cfg, 24 * dt, dt, wd)
    batch, fleet = build_fleet_batch(
        homes, cfg, 24 * dt, dt,
        int(cfg["home"]["hems"]["sub_subhourly_steps"]))
    eng = make_sharded_engine(batch, env, cfg, 0, fleet=fleet)
    assert eng.true_n_homes == n * C and eng.n_communities == C
    assert eng.bucketed and len(eng.bucket_info()) <= 4  # flat in C
    for b in eng.bucket_info():
        assert b["n_slots"] % 8 == 0

    state = eng.init_state()
    rps = np.zeros((2, eng.params.horizon), dtype=np.float32)
    state, outs = eng.run_chunk(state, 0, rps)
    jax.block_until_ready(outs.agg_load)

    cols = eng.real_home_cols
    assert len(set(cols.tolist())) == n * C
    solved = np.asarray(outs.correct_solve)[:, cols]
    assert float(solved.mean()) >= 0.95
    for leaf, name in zip(outs, outs._fields):
        assert np.all(np.isfinite(np.asarray(leaf))), f"non-finite {name}"
    agg = np.asarray(outs.agg_load)
    per_home = np.asarray(outs.p_grid)[:, cols].sum(axis=1)
    np.testing.assert_allclose(agg, per_home, rtol=2e-4)
    # Per-community aggregates through the (community, col) mapping: each
    # community contributes a sane, nonzero share of the fleet load.
    pairs = eng.real_home_pairs
    for c in range(C):
        ccols = pairs[pairs[:, 0] == c, 1]
        assert ccols.shape == (n,)
        assert np.asarray(outs.p_grid)[:, ccols].sum() != 0.0
