"""Checkpoint/resume tests — mid-simulation resume must be bit-exact.

The reference can only restart from t=0 (its checkpoints are write-only
outputs, dragg/aggregator.py:776-778); these tests prove the new capability:
an interrupted run, resumed from the persisted scan carry, produces results
identical to an uninterrupted run."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from dragg_tpu.checkpoint import load_pytree, save_pytree
from dragg_tpu.config import default_config


def _cfg(**sim_over):
    cfg = default_config()
    cfg["community"]["total_number_homes"] = 4
    cfg["community"]["homes_pv"] = 1
    cfg["community"]["homes_battery"] = 1
    cfg["community"]["homes_pv_battery"] = 1
    cfg["simulation"]["start_datetime"] = "2015-01-01 00"
    cfg["simulation"]["end_datetime"] = "2015-01-03 00"  # 2 days → 2 daily chunks
    cfg["simulation"]["checkpoint_interval"] = "daily"
    cfg["home"]["hems"]["prediction_horizon"] = 2
    cfg["tpu"]["admm_iters"] = 200
    cfg["simulation"].update(sim_over)
    return cfg


def test_pytree_roundtrip(tmp_path):
    from dragg_tpu.rl.core import init_carry, params_from_config

    carry = init_carry(params_from_config(default_config()), seed=9)
    path = str(tmp_path / "carry.npz")
    save_pytree(path, carry)
    # Template with different values, same structure.
    template = init_carry(params_from_config(default_config()), seed=1)
    loaded = load_pytree(path, template)
    for a, b in zip(carry, loaded):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pytree_shape_mismatch_raises(tmp_path):
    tree = {"a": jnp.zeros((3,)), "b": jnp.ones((2, 2))}
    path = str(tmp_path / "t.npz")
    save_pytree(path, tree)
    bad = {"a": jnp.zeros((4,)), "b": jnp.ones((2, 2))}
    with pytest.raises(ValueError, match="shape"):
        load_pytree(path, bad)
    with pytest.raises(ValueError, match="leaves"):
        load_pytree(path, {"a": jnp.zeros((3,))})


def _per_home_series(results: dict) -> dict:
    return {
        name: {k: v for k, v in d.items() if isinstance(v, list)}
        for name, d in results.items() if name != "Summary"
    }


def test_baseline_resume_bit_exact(tmp_path):
    from dragg_tpu.aggregator import Aggregator

    # Uninterrupted reference run.
    full = Aggregator(_cfg(), data_dir=None, outputs_dir=str(tmp_path / "full"))
    full.run()
    with open(os.path.join(full.run_dir, "baseline", "results.json")) as f:
        expected = json.load(f)

    # Interrupted run: stop after the first daily chunk...
    out2 = str(tmp_path / "resumed")
    part = Aggregator(_cfg(), data_dir=None, outputs_dir=out2)
    part.stop_after_chunks = 1
    part.run()
    ckpt_root = os.path.join(part.run_dir, "baseline", "checkpoint")
    latest = open(os.path.join(ckpt_root, "LATEST")).read().strip()
    assert os.path.isfile(os.path.join(ckpt_root, latest, "state.npz"))
    partial = json.load(open(os.path.join(part.run_dir, "baseline", "results.json")))
    n_partial = len(partial[next(n for n in partial if n != "Summary")]["p_grid_opt"])
    assert n_partial < full.num_timesteps

    # ...then resume in a fresh process-equivalent Aggregator.
    res = Aggregator(_cfg(resume=True), data_dir=None,
                     outputs_dir=out2)
    res.run()
    with open(os.path.join(res.run_dir, "baseline", "results.json")) as f:
        got = json.load(f)

    exp_series = _per_home_series(expected)
    got_series = _per_home_series(got)
    assert set(exp_series) == set(got_series)
    for name in exp_series:
        for key in exp_series[name]:
            np.testing.assert_array_equal(
                np.asarray(exp_series[name][key]), np.asarray(got_series[name][key]),
                err_msg=f"{name}.{key} diverged across resume",
            )
    np.testing.assert_array_equal(
        np.asarray(expected["Summary"]["p_grid_aggregate"]),
        np.asarray(got["Summary"]["p_grid_aggregate"]),
    )


def test_completed_run_clears_checkpoint_and_rerun_is_clean(tmp_path):
    """A finished run must not leave a stale checkpoint behind: re-invoking
    with resume=true starts fresh and produces identical full-length
    results instead of appending duplicate chunks."""
    from dragg_tpu.aggregator import Aggregator

    out = str(tmp_path / "outputs")
    a = Aggregator(_cfg(resume=True), data_dir=None, outputs_dir=out)
    a.run()
    ckpt_root = os.path.join(a.run_dir, "baseline", "checkpoint")
    assert not os.path.isdir(ckpt_root)
    expected = json.load(open(os.path.join(a.run_dir, "baseline", "results.json")))

    b = Aggregator(_cfg(resume=True), data_dir=None, outputs_dir=out)
    b.run()
    got = json.load(open(os.path.join(b.run_dir, "baseline", "results.json")))
    for name, d in got.items():
        if name == "Summary":
            continue
        assert len(d["p_grid_opt"]) == b.num_timesteps
    np.testing.assert_array_equal(
        np.asarray(expected["Summary"]["p_grid_aggregate"]),
        np.asarray(got["Summary"]["p_grid_aggregate"]),
    )


def test_resume_rejects_mismatched_config(tmp_path):
    """A checkpoint written under one run shape (num_timesteps / n_homes /
    horizon) must be ignored — not half-loaded into wrong-length
    bookkeeping arrays — when the config changes between runs."""
    from dragg_tpu.aggregator import Aggregator

    out = str(tmp_path / "outputs")
    part = Aggregator(_cfg(), data_dir=None, outputs_dir=out)
    part.stop_after_chunks = 1
    part.run()
    assert part.timestep < part.num_timesteps  # checkpoint exists mid-run

    # Same run dir, longer simulation → different num_timesteps.
    res = Aggregator(_cfg(resume=True, end_datetime="2015-01-04 00"),
                     data_dir=None, outputs_dir=out)
    res.run()
    assert res.resumed_from is None  # started fresh, no broadcast errors
    got = json.load(open(os.path.join(res.run_dir, "baseline", "results.json")))
    for name, d in got.items():
        if name == "Summary":
            continue
        assert len(d["p_grid_opt"]) == res.num_timesteps


def test_resume_rejects_warm_carry_width_change(tmp_path):
    """The warm-start carry is zero-width on the default IPM path and
    (n, nvar) with ipm_warm_start enabled (engine.init_state).  A solver
    CHANGE lands in a different run dir (the dir name embeds the solver),
    but the ipm_warm_start toggle does not — so a checkpoint written with
    it on, resumed with it off, must be INVALIDATED via run_shape instead
    of crashing load_pytree's leaf-shape check (advisor finding, r4)."""
    from dragg_tpu.aggregator import Aggregator

    def cfg_warm(warm, **over):
        cfg = _cfg(**over)
        cfg["home"]["hems"]["solver"] = "ipm"
        cfg["tpu"]["ipm_warm_start"] = warm
        return cfg

    out = str(tmp_path / "outputs")
    part = Aggregator(cfg_warm(True), data_dir=None, outputs_dir=out)
    part.stop_after_chunks = 1
    part.run()
    assert part.timestep < part.num_timesteps  # checkpoint exists mid-run

    res = Aggregator(cfg_warm(False, resume=True),
                     data_dir=None, outputs_dir=out)
    res.run()
    assert res.resumed_from is None  # invalidated, started fresh
    got = json.load(open(os.path.join(res.run_dir, "baseline", "results.json")))
    for name, d in got.items():
        if name == "Summary":
            continue
        assert len(d["p_grid_opt"]) == res.num_timesteps


def test_checkpoint_survives_preexisting_final_dir(tmp_path):
    """Kill-window regression (ADVICE r1): a complete ckpt dir left behind
    with LATEST still pointing at the previous checkpoint must not make the
    next save_checkpoint at that timestep fail."""
    from dragg_tpu.aggregator import Aggregator

    out = str(tmp_path / "outputs")
    # 3 days → checkpoints after chunk 1 and chunk 2.
    cfg = _cfg(end_datetime="2015-01-04 00")
    part = Aggregator(cfg, data_dir=None, outputs_dir=out)
    part.stop_after_chunks = 1
    part.run()
    ckpt_root = os.path.join(part.run_dir, "baseline", "checkpoint")
    latest = open(os.path.join(ckpt_root, "LATEST")).read().strip()
    # Simulate the kill window: the NEXT checkpoint dir (second daily
    # boundary = 2× the first) exists complete, but LATEST was never
    # advanced past the current one.
    stale = os.path.join(ckpt_root, "ckpt_t%08d" % (2 * part.timestep))
    os.makedirs(stale)
    with open(os.path.join(stale, "junk.txt"), "w") as f:
        f.write("leftover")

    res = Aggregator(_cfg(resume=True, end_datetime="2015-01-04 00"),
                     data_dir=None, outputs_dir=out)
    res.run()  # must re-reach that timestep and overwrite the stale dir
    assert res.resumed_from is not None and res.resumed_from.endswith(latest)
    got = json.load(open(os.path.join(res.run_dir, "baseline", "results.json")))
    for name, d in got.items():
        if name == "Summary":
            continue
        assert len(d["p_grid_opt"]) == res.num_timesteps


@pytest.mark.slow  # round-11 tier-1 budget trim: tier-1 keeps test_baseline_resume_bit_exact (same resume machinery); the rl_agg variant re-runs it with RL training on top
def test_rl_agg_resume_bit_exact(tmp_path):
    from dragg_tpu.aggregator import Aggregator

    cfg_kw = dict(run_rbo_mpc=False, run_rl_agg=True)
    full = Aggregator(_cfg(**cfg_kw), data_dir=None, outputs_dir=str(tmp_path / "full"))
    full.run()
    expected = json.load(open(os.path.join(full.run_dir, "rl_agg", "results.json")))

    out2 = str(tmp_path / "resumed")
    part = Aggregator(_cfg(**cfg_kw), data_dir=None, outputs_dir=out2)
    part.stop_after_chunks = 1
    part.run()
    res = Aggregator(_cfg(resume=True, **cfg_kw),
                     data_dir=None, outputs_dir=out2)
    res.run()
    got = json.load(open(os.path.join(res.run_dir, "rl_agg", "results.json")))

    np.testing.assert_array_equal(
        np.asarray(expected["Summary"]["p_grid_aggregate"]),
        np.asarray(got["Summary"]["p_grid_aggregate"]),
    )
    np.testing.assert_array_equal(
        np.asarray(expected["Summary"]["RP"]), np.asarray(got["Summary"]["RP"]),
    )
    # Agent telemetry also continues seamlessly.
    exp_rl = json.load(open(os.path.join(full.run_dir, "rl_agg", "utility_agent-results.json")))
    got_rl = json.load(open(os.path.join(res.run_dir, "rl_agg", "utility_agent-results.json")))
    assert len(exp_rl["reward"]) == len(got_rl["reward"]) == full.num_timesteps
    np.testing.assert_allclose(exp_rl["reward"], got_rl["reward"], rtol=1e-6)


@pytest.mark.slow  # 3 fleet RL runs; light sibling:
                   # tests/test_rl_fleet.py test_fleet_agent_carry_checkpoint_roundtrip
def test_fleet_rl_agg_resume_bit_exact(tmp_path):
    """Satellite (ROADMAP item 1): the BATCHED fleet agent carry — here
    the shared Flax DDPG twin-Q core's nested param/Adam pytrees plus
    the (C,)-batched env carry — checkpoints mid-training and resumes
    bit-exact: prices, per-community prices, per-home series, and the
    agent telemetry all match the uninterrupted run."""
    from dragg_tpu.aggregator import Aggregator

    def cfg_(resume=False):
        cfg = _cfg(run_rbo_mpc=False, run_rl_agg=True, resume=resume)
        cfg["fleet"]["communities"] = 2
        cfg["rl"]["parameters"]["agent"] = "ddpg"
        cfg["telemetry"]["enabled"] = False
        return cfg

    full = Aggregator(cfg_(), data_dir="",
                      outputs_dir=str(tmp_path / "full"))
    full.run()
    exp = json.load(open(os.path.join(full.run_dir, "rl_agg",
                                      "results.json")))

    out2 = str(tmp_path / "resumed")
    part = Aggregator(cfg_(resume=True), data_dir="", outputs_dir=out2)
    part.stop_after_chunks = 1
    part.run()
    assert part.timestep == 24
    res = Aggregator(cfg_(resume=True), data_dir="", outputs_dir=out2)
    res.run()
    assert res.resumed_from is not None
    got = json.load(open(os.path.join(res.run_dir, "rl_agg",
                                      "results.json")))
    np.testing.assert_array_equal(
        np.asarray(exp["Summary"]["p_grid_aggregate"]),
        np.asarray(got["Summary"]["p_grid_aggregate"]))
    np.testing.assert_array_equal(np.asarray(exp["Summary"]["RP"]),
                                  np.asarray(got["Summary"]["RP"]))
    np.testing.assert_array_equal(
        np.asarray(exp["Summary"]["fleet_rl"]["RP_by_community"]),
        np.asarray(got["Summary"]["fleet_rl"]["RP_by_community"]))
    for h in (k for k in exp if k != "Summary"):
        for series, vals in exp[h].items():
            if isinstance(vals, list):
                assert vals == got[h][series], (h, series)
    exp_rl = json.load(open(os.path.join(
        full.run_dir, "rl_agg", "utility_agent-results.json")))
    got_rl = json.load(open(os.path.join(
        res.run_dir, "rl_agg", "utility_agent-results.json")))
    assert len(exp_rl["reward"]) == len(got_rl["reward"]) \
        == full.num_timesteps
    np.testing.assert_allclose(exp_rl["reward"], got_rl["reward"],
                               rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(exp_rl["action_by_community"]),
        np.asarray(got_rl["action_by_community"]), rtol=1e-6)


def test_resume_across_sharding_change_starts_fresh(tiny_config, tmp_path):
    """A checkpoint written by the sharded engine (8 padded slots) must be
    rejected gracefully — not crash in load_pytree — when the run is retried
    unsharded (different slot count)."""
    import copy

    from dragg_tpu.aggregator import Aggregator

    cfg = copy.deepcopy(tiny_config)
    cfg["simulation"]["end_datetime"] = "2015-01-03 00"
    cfg["simulation"]["resume"] = True
    cfg["simulation"]["checkpoint_interval"] = "daily"
    out = str(tmp_path / "out")

    agg = Aggregator(copy.deepcopy(cfg), data_dir=None, outputs_dir=out)
    agg.stop_after_chunks = 1
    agg.run()  # auto-shards on the 8-device mesh; one checkpoint written
    assert agg.timestep == 24

    cfg2 = copy.deepcopy(cfg)
    cfg2["tpu"]["sharded"] = False
    agg2 = Aggregator(cfg2, data_dir=None, outputs_dir=out)
    agg2.run()  # must start fresh (slot-count mismatch), not raise
    assert agg2.resumed_from is None
    assert agg2.timestep == agg2.num_timesteps


def test_sharded_config_validation(tiny_config):
    import copy

    import pytest

    from dragg_tpu.aggregator import Aggregator

    cfg = copy.deepcopy(tiny_config)
    cfg["tpu"]["sharded"] = "yes"
    agg = Aggregator(cfg, data_dir=None, outputs_dir="/tmp/shv")
    with pytest.raises(ValueError, match="sharded"):
        agg.run()
