"""The unified telemetry layer (round-7 tentpole): golden schema over a
tiny CPU-mesh run's events.jsonl, disabled-mode overhead A/B, registry ⇄
docs coverage, the shared supervised stream, and the dashboard's /live +
/metrics.json endpoints against an in-progress run dir."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from dragg_tpu import telemetry
from dragg_tpu.resilience.taxonomy import FAILURE_KINDS

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ENVELOPE = {"event", "t", "mono", "pid", "seq"}


@pytest.fixture(autouse=True)
def _isolated_bus():
    """Every test starts and ends with no process bus (close_run also
    re-arms the $DRAGG_TELEMETRY_DIR auto-join)."""
    telemetry.close_run()
    yield
    telemetry.close_run()


def _tiny_cfg():
    from dragg_tpu.config import default_config

    cfg = default_config()
    cfg["community"]["total_number_homes"] = 3
    cfg["community"]["homes_pv"] = 0
    cfg["simulation"]["end_datetime"] = "2015-01-01 06"
    cfg["simulation"]["checkpoint_interval"] = "hourly"
    cfg["home"]["hems"]["prediction_horizon"] = 2
    return cfg


# ----------------------------------------------------------- registry/docs
def test_registry_and_docs_cover_each_other():
    """docs/telemetry.md lists every registered name, and every
    backticked dotted name in its tables is registered — the doc cannot
    drift from the registry in either direction."""
    import re

    with open(os.path.join(ROOT, "docs", "telemetry.md")) as f:
        doc = f.read()
    for name in (*telemetry.EVENTS, *telemetry.METRICS):
        assert f"`{name}`" in doc, f"{name} undocumented in docs/telemetry.md"
    documented = {m for m in re.findall(r"`([a-z_]+(?:\.[A-Za-z_]+)+)`", doc)
                  if m.split(".")[0] in ("run", "chunk", "span", "bench",
                                         "probe", "heartbeat", "supervisor",
                                         "degrade", "failure", "telemetry",
                                         "engine", "sim", "solver",
                                         "compile")}
    registered = set(telemetry.EVENTS) | set(telemetry.METRICS) \
        | {"telemetry.enabled", "telemetry.dir", "span.s"}
    stray = {d for d in documented if d not in registered
             and not d.startswith(("telemetry.", "docs.", "tools.",
                                   "dragg_tpu.", "bench.py"))}
    assert not stray, f"docs/telemetry.md names unregistered entries: {stray}"


def test_failure_events_track_taxonomy():
    """The failure.* event family stays in sync with the resilience
    taxonomy (the registry is a literal table, so this is the guard)."""
    for kind in FAILURE_KINDS:
        assert f"failure.{kind}" in telemetry.EVENTS
    extra = {e for e in telemetry.EVENTS if e.startswith("failure.")} \
        - {f"failure.{k}" for k in FAILURE_KINDS}
    assert not extra, f"registry has failure events with no taxonomy kind: {extra}"


def test_unregistered_names_raise():
    """Name discipline holds even with no bus open: a typo fails fast
    instead of silently fragmenting the stream."""
    with pytest.raises(ValueError, match="unregistered telemetry event"):
        telemetry.emit("no.such.event")
    with pytest.raises(ValueError, match="unregistered telemetry metric"):
        telemetry.observe("no.such.metric", 1.0)
    with pytest.raises(ValueError, match="registered as a gauge"):
        telemetry.observe("engine.solve_rate", 1.0)  # gauge, not histogram
    with pytest.raises(ValueError):
        telemetry.span("engine.solve_rate")  # spans need a histogram


# ------------------------------------------------------------- bus basics
def test_span_and_snapshot_roundtrip(tmp_path):
    telemetry.init_run(str(tmp_path))
    with telemetry.span("engine.chunk_device_s") as sp:
        time.sleep(0.01)
    assert sp.s is not None and sp.s >= 0.01
    telemetry.inc("engine.repair_failed", 2)
    telemetry.set_gauge("engine.solve_rate", 0.75)
    path = telemetry.write_snapshot()
    snap = json.load(open(path))
    assert snap["counters"]["engine.repair_failed"] == 2
    assert snap["gauges"]["engine.solve_rate"] == 0.75
    h = snap["histograms"]["engine.chunk_device_s"]
    assert h["count"] == 1 and h["last"] == pytest.approx(sp.s)
    assert h["samples"] == [pytest.approx(sp.s)]
    # The span also left a typed event on the stream.
    recs = [json.loads(l) for l in open(os.path.join(
        str(tmp_path), telemetry.EVENTS_FILE))]
    assert recs[-1]["event"] == "span"
    assert recs[-1]["name"] == "engine.chunk_device_s"


def test_disabled_overhead_negligible(tmp_path):
    """Disabled-mode emits are a registry lookup + one global load —
    the A/B pins them well under the enabled (file-writing) cost and
    under an absolute 10 µs/call ceiling."""
    n_off = 50_000
    t0 = time.perf_counter()
    for _ in range(n_off):
        telemetry.emit("chunk.done", t0=0, t1=1, solve_rate=1.0)
        telemetry.observe("engine.solve_iters", 1.0)
    off_per_call = (time.perf_counter() - t0) / (2 * n_off)

    telemetry.init_run(str(tmp_path))
    n_on = 2_000
    t0 = time.perf_counter()
    for _ in range(n_on):
        telemetry.emit("chunk.done", t0=0, t1=1, solve_rate=1.0)
        telemetry.observe("engine.solve_iters", 1.0)
    on_per_call = (time.perf_counter() - t0) / (2 * n_on)

    assert off_per_call < 10e-6, f"disabled emit {off_per_call*1e6:.2f} µs"
    assert off_per_call < on_per_call, (
        f"disabled ({off_per_call*1e6:.2f} µs) not cheaper than enabled "
        f"({on_per_call*1e6:.2f} µs)")


def test_env_dir_auto_join(tmp_path, monkeypatch):
    """$DRAGG_TELEMETRY_DIR joins the stream lazily — how supervised
    children (which never call init_run) land on the parent's file."""
    monkeypatch.setenv(telemetry.ENV_DIR, str(tmp_path))
    telemetry.emit("heartbeat.beat", progress={"x": 1})
    recs = [json.loads(l) for l in open(os.path.join(
        str(tmp_path), telemetry.EVENTS_FILE))]
    assert recs[0]["event"] == "heartbeat.beat"
    assert recs[0]["progress"] == {"x": 1}


# -------------------------------------------------------- golden run schema
def test_tiny_run_events_golden_schema(tmp_path):
    """A default tiny CPU-mesh run produces <run_dir>/events.jsonl +
    metrics.json matching the docs/telemetry.md schema: enveloped
    records, registered names only, per-process monotone seq/mono, and
    the engine's device-side solver telemetry on every chunk.done."""
    from dragg_tpu.aggregator import Aggregator

    agg = Aggregator(_tiny_cfg(), data_dir=None,
                     outputs_dir=str(tmp_path / "out"))
    agg.run()

    events = os.path.join(agg.run_dir, telemetry.EVENTS_FILE)
    metrics = os.path.join(agg.run_dir, telemetry.METRICS_FILE)
    assert os.path.isfile(events) and os.path.isfile(metrics)

    recs = [json.loads(line) for line in open(events)]
    assert recs, "events.jsonl is empty"
    last_seq = {}
    last_mono = {}
    for rec in recs:
        assert ENVELOPE <= set(rec), f"envelope missing in {rec}"
        assert rec["event"] in telemetry.EVENTS, rec["event"]
        assert rec["seq"] > last_seq.get(rec["pid"], 0)
        assert rec["mono"] >= last_mono.get(rec["pid"], 0.0)
        last_seq[rec["pid"]] = rec["seq"]
        last_mono[rec["pid"]] = rec["mono"]

    by_event = {}
    for rec in recs:
        by_event.setdefault(rec["event"], []).append(rec)
    assert by_event["run.start"][0]["homes"] == 3
    assert by_event["run.end"][-1]["completed"] is True
    chunks = by_event["chunk.done"]
    assert len(chunks) == 6  # hourly checkpoints over a 6 h window
    for c in chunks:
        for field in ("t0", "t1", "n_steps", "solve_rate", "solver_iters",
                      "r_prim_max", "r_dual_max", "repair_failed",
                      "device_s", "steps_per_s"):
            assert field in c, f"chunk.done missing {field}"
        assert 0.0 <= c["solve_rate"] <= 1.0
        assert c["r_prim_max"] >= 0.0 and c["r_dual_max"] >= 0.0
    assert chunks[-1]["t1"] == 6

    snap = json.load(open(metrics))
    for section, table in (("counters", telemetry.METRICS),
                           ("gauges", telemetry.METRICS),
                           ("histograms", telemetry.METRICS)):
        for name in snap[section]:
            assert name in table, f"unregistered {section} name {name}"
    assert snap["gauges"]["sim.timestep"] == 6
    assert snap["histograms"]["engine.chunk_device_s"]["count"] == 6
    assert 0.0 <= snap["gauges"]["engine.solve_rate"] <= 1.0


def test_telemetry_disabled_writes_nothing(tmp_path):
    cfg = _tiny_cfg()
    cfg["simulation"]["end_datetime"] = "2015-01-01 02"
    cfg["telemetry"] = {"enabled": False}
    from dragg_tpu.aggregator import Aggregator

    agg = Aggregator(cfg, data_dir=None, outputs_dir=str(tmp_path / "out"))
    agg.run()
    assert not os.path.isfile(os.path.join(agg.run_dir,
                                           telemetry.EVENTS_FILE))
    assert not os.path.isfile(os.path.join(agg.run_dir,
                                           telemetry.METRICS_FILE))


# -------------------------------------------------- shared supervised stream
def test_supervisor_and_child_share_one_stream(tmp_path):
    """The supervisor's lifecycle records and the child's beats land in
    the SAME events.jsonl: the parent exports $DRAGG_TELEMETRY_DIR, the
    child auto-joins (the round-7 'one forensic file per run' contract)."""
    from dragg_tpu.resilience.supervisor import run_supervised

    telemetry.init_run(str(tmp_path))
    child = ("import sys; sys.path.insert(0, %r); "
             "from dragg_tpu.resilience.heartbeat import beat; "
             "beat({'stage': 'child-proof'})" % ROOT)
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    res = run_supervised([sys.executable, "-c", child], deadline_s=60.0,
                         label="telemetry-child", env=env)
    assert res.ok, res.stderr_tail
    recs = [json.loads(l) for l in open(os.path.join(
        str(tmp_path), telemetry.EVENTS_FILE))]
    names = [r["event"] for r in recs]
    assert "supervisor.launch" in names
    assert "supervisor.exit" in names
    beats = [r for r in recs if r["event"] == "heartbeat.beat"]
    assert beats and beats[0]["progress"] == {"stage": "child-proof"}
    assert beats[0]["pid"] != os.getpid(), "beat must come from the child"


def test_probe_watcher_emits_jsonl_transcript(tmp_path):
    """tools/tpu_probe.py routes its outage/uptime transcript through
    the telemetry schema (probe.verdict + failure.<kind>) — the
    watcher, supervisor, and runbook share one forensic format."""
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["DRAGG_FAULT_INJECT"] = "probe_down"  # deterministic, no subprocess probe
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "tpu_probe.py"),
         "--log", str(tmp_path / "probe_log.txt"),
         "--events-dir", str(tmp_path), "--classify"],
        capture_output=True, text=True, timeout=120, env=env, cwd=ROOT)
    assert proc.returncode == 1, proc.stdout + proc.stderr  # DOWN
    recs = [json.loads(l) for l in open(os.path.join(
        str(tmp_path), telemetry.EVENTS_FILE))]
    verdicts = [r for r in recs if r["event"] == "probe.verdict"]
    assert verdicts and verdicts[0]["alive"] is False
    assert verdicts[0]["kind"] == "TUNNEL_DOWN"
    fails = [r for r in recs if r["event"] == "failure.TUNNEL_DOWN"]
    assert fails and fails[0]["source"] == "probe"
    # Legacy text transcript still appended alongside.
    assert "DOWN" in open(tmp_path / "probe_log.txt").read()


# ------------------------------------------------------- dashboard live view
def _write_in_progress_run(outputs_dir: str) -> str:
    """An in-progress run dir: events.jsonl, no metrics.json, no
    results.json — invisible to figure discovery, visible to /live."""
    run_dir = os.path.join(outputs_dir, "2015-01-01T00_2015-01-02T00",
                           "all-homes_3-horizon_2-interval_60-10-solver_ipm",
                           "version-test")
    telemetry.init_run(run_dir)
    telemetry.emit("run.start", case="baseline", homes=3, horizon=2,
                   solver="ipm", run_dir=run_dir)
    telemetry.emit("chunk.done", t0=0, t1=24, n_steps=24, solve_rate=0.99,
                   solver_iters=12.0, r_prim_max=1e-4, r_dual_max=1e-5,
                   repair_failed=0, device_s=1.5, steps_per_s=16.0)
    telemetry.close_run()
    return run_dir


def test_dashboard_live_and_metrics_endpoints(tmp_path):
    from dragg_tpu.dashboard import Dashboard, make_handler
    from http.server import ThreadingHTTPServer

    outputs = str(tmp_path / "out")
    run_dir = _write_in_progress_run(outputs)
    dash = Dashboard(outputs_dir=outputs)

    # Render side: the stream is discovered as in-progress and the
    # partial snapshot folds from the events (no metrics.json yet).
    runs = dash.live_runs()
    assert len(runs) == 1 and runs[0]["final"] is False
    snap = dash.metrics_snapshot(runs[0])
    assert snap["final"] is False
    assert snap["by_event"] == {"run.start": 1, "chunk.done": 1}
    assert snap["last"]["chunk.done"]["solve_rate"] == 0.99
    html = dash.live_html("")
    assert "chunk.done" in html and "in progress" in html

    # HTTP side: /live and /metrics.json answer over a real socket.
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(dash))
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    try:
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        opener = urllib.request.build_opener(
            urllib.request.ProxyHandler({}))
        live = opener.open(f"{base}/live", timeout=30).read().decode()
        assert "chunk.done" in live
        m = json.loads(opener.open(f"{base}/metrics.json?run=0",
                                   timeout=30).read())
        assert m["final"] is False and m["by_event"]["chunk.done"] == 1
        # Once the run finishes (metrics.json lands), the endpoint
        # serves the final snapshot instead of the event fold.
        telemetry.init_run(run_dir)
        telemetry.set_gauge("sim.timestep", 24)
        telemetry.write_snapshot()
        telemetry.close_run()
        m2 = json.loads(opener.open(f"{base}/metrics.json?run=0",
                                    timeout=30).read())
        assert m2["final"] is True
        assert m2["gauges"]["sim.timestep"] == 24
    finally:
        httpd.shutdown()
        httpd.server_close()
