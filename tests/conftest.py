"""Test configuration.

Multi-chip behavior is tested on a virtual 8-device CPU mesh
(``xla_force_host_platform_device_count``), the pattern SURVEY.md §4(f)
prescribes; single-chip numerics run on the same CPU backend so CI needs no
TPU.  Must set env vars before jax is imported anywhere.
"""

import os

# Force the 8-device virtual CPU mesh via jax.config (not env vars): the
# image's sitecustomize imports jax and pins the tunneled single-chip TPU
# platform before conftest runs, so JAX_PLATFORMS / XLA_FLAGS set here are
# too late — the config API still works until a backend is initialized.
os.environ["JAX_PLATFORMS"] = "cpu"
# Pre-0.5 jax has no jax_num_cpu_devices config; the XLA flag is the
# same mesh.  Set BEFORE the import — in images whose sitecustomize
# already imported jax this is too late and the config call below takes
# over instead.
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # pre-0.5 jax: the XLA_FLAGS route above applies
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from dragg_tpu.config import default_config  # noqa: E402


@pytest.fixture
def tiny_config():
    """A small, fast community config: 6 homes (1 of each special type),
    4h horizon, 24h sim."""
    cfg = default_config()
    cfg["community"]["total_number_homes"] = 6
    cfg["community"]["homes_pv"] = 1
    cfg["community"]["homes_battery"] = 1
    cfg["community"]["homes_pv_battery"] = 1
    cfg["simulation"]["start_datetime"] = "2015-01-01 00"
    cfg["simulation"]["end_datetime"] = "2015-01-02 00"
    cfg["home"]["hems"]["prediction_horizon"] = 4
    return cfg


@pytest.fixture
def rng():
    return np.random.RandomState(0)
