"""Test configuration.

Multi-chip behavior is tested on a virtual 8-device CPU mesh
(``xla_force_host_platform_device_count``), the pattern SURVEY.md §4(f)
prescribes; single-chip numerics run on the same CPU backend so CI needs no
TPU.  Must set env vars before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from dragg_tpu.config import default_config  # noqa: E402


@pytest.fixture
def tiny_config():
    """A small, fast community config: 6 homes (1 of each special type),
    4h horizon, 24h sim."""
    cfg = default_config()
    cfg["community"]["total_number_homes"] = 6
    cfg["community"]["homes_pv"] = 1
    cfg["community"]["homes_battery"] = 1
    cfg["community"]["homes_pv_battery"] = 1
    cfg["simulation"]["start_datetime"] = "2015-01-01 00"
    cfg["simulation"]["end_datetime"] = "2015-01-02 00"
    cfg["home"]["hems"]["prediction_horizon"] = 4
    return cfg


@pytest.fixture
def rng():
    return np.random.RandomState(0)
