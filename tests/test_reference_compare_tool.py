"""The home-by-home diff harness (tools/compare_reference.py) — CI
exercise of the alignment + statistics logic so it cannot rot while the
literal-reference run waits for a dockerized environment
(docs/reference_comparison.md layer 3)."""

import json
import os
import subprocess
import sys

import pytest

from dragg_tpu.aggregator import Aggregator

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cfg, outdir):
    agg = Aggregator(config=cfg, outputs_dir=str(outdir))
    agg.run()
    return os.path.join(agg.run_dir, "baseline", "results.json")


@pytest.mark.slow
def test_compare_tool_identical_and_perturbed(tiny_config, tmp_path):
    import copy

    cfg = copy.deepcopy(tiny_config)
    res_a = _run(cfg, tmp_path / "a")

    # Same seed/config → bit-identical series → all-zero diffs, bounded.
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "compare_reference.py"),
         res_a, res_a],
        capture_output=True, text=True, timeout=120, check=True)
    d = json.loads(out.stdout)
    assert d["n_shared"] == d["n_homes_ref"] == d["n_homes_ours"] > 0
    assert d["bounded"] is True
    assert all(s["max_abs"] == 0.0 for s in d["series"].values())
    # Every compared series must actually exist in the results schema —
    # a renamed/missing key must surface as missing_homes, and the
    # shipped schema must have none (caught the cost/cost_opt drift,
    # round-5 verify).
    assert all("missing_homes" not in s for s in d["series"].values()), d["series"]

    # Same seed (names align) but a different horizon → different plans →
    # nonzero divergence must be reported, not masked by the alignment.
    cfg2 = copy.deepcopy(tiny_config)
    cfg2["home"]["hems"]["prediction_horizon"] = 2
    res_b = _run(cfg2, tmp_path / "b")
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "compare_reference.py"),
         res_a, res_b],
        capture_output=True, text=True, timeout=120, check=True)
    d = json.loads(out.stdout)
    assert d["n_shared"] > 0  # names coincide (same count, same order)
    assert max(s["max_abs"] for s in d["series"].values()) > 0.0


def test_run_reference_refuses_without_stack():
    """--run-reference must fail fast with the Docker pointer when the
    reference stack is absent (it is, in this image)."""
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "compare_reference.py"),
         "--run-reference"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode != 0
    assert "reference stack unavailable" in (out.stderr + out.stdout)
