// statebus — in-process C++ replacement for the reference's Redis server.
//
// The reference routes ALL inter-component state through a C Redis server
// over TCP (dragg/redis_client.py:13-25; schema: series lists, the
// current_values hash, the reward_price list, per-home result hashes —
// dragg/aggregator.py:640-675, dragg/mpc_calc.py:100-132).  The TPU-native
// engine eliminates that bus from the hot loop entirely (state is device
// arrays), but the host runtime still offers the same verbs for
// reference-compatible orchestration and for multi-process CPU-reference
// mode: set/get, hset/hget/hgetall, rpush/lrange/llen, del, flushall.
//
// Design: one process-wide store keyed by (db, key); values are either a
// string, a vector<string> (list), or an unordered_map<string,string>
// (hash) — exactly Redis's model restricted to the verbs the reference
// uses.  Thread-safe via a shared_mutex (readers concurrent, writers
// exclusive), matching the structural race-safety the reference relies on
// (workers write disjoint keys; readers join first — SURVEY.md §5.2).
//
// C ABI: every entry point is extern "C" with C-string I/O so ctypes can
// bind without any build-time Python dependency.  Returned strings are
// heap-allocated copies; callers free them with sb_free().

#include <cstring>
#include <cstdint>
#include <map>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Value {
    // tag: 0 = string, 1 = list, 2 = hash
    int tag = 0;
    std::string str;
    std::vector<std::string> list;
    // std::map keeps hgetall output deterministic (sorted by field).
    std::map<std::string, std::string> hash;
};

struct Store {
    std::unordered_map<std::string, Value> data;
    std::shared_mutex mu;
};

Store &store() {
    static Store s;
    return s;
}

char *dup_cstr(const std::string &s) {
    char *out = static_cast<char *>(std::malloc(s.size() + 1));
    if (out != nullptr) {
        std::memcpy(out, s.c_str(), s.size() + 1);
    }
    return out;
}

// Serialize a list of strings with length prefixes: "<n>\n<len> <bytes>\n...".
// Length-prefixed framing survives arbitrary payload bytes (values may
// contain newlines or separators).
std::string frame(const std::vector<std::pair<std::string, std::string>> &kvs,
                  bool pairs) {
    std::string out = std::to_string(kvs.size());
    out.push_back('\n');
    for (const auto &kv : kvs) {
        out += std::to_string(kv.first.size());
        out.push_back(' ');
        out += kv.first;
        out.push_back('\n');
        if (pairs) {
            out += std::to_string(kv.second.size());
            out.push_back(' ');
            out += kv.second;
            out.push_back('\n');
        }
    }
    return out;
}

}  // namespace

extern "C" {

void sb_free(char *p) { std::free(p); }

void sb_flushall() {
    std::unique_lock lock(store().mu);
    store().data.clear();
}

void sb_del(const char *key) {
    std::unique_lock lock(store().mu);
    store().data.erase(key);
}

int sb_exists(const char *key) {
    std::shared_lock lock(store().mu);
    return store().data.count(key) ? 1 : 0;
}

// ---------------------------------------------------------------- strings
void sb_set(const char *key, const char *val) {
    std::unique_lock lock(store().mu);
    Value &v = store().data[key];
    v.tag = 0;
    v.str = val;
    v.list.clear();
    v.hash.clear();
}

// Returns NULL when the key is absent or not a string.
char *sb_get(const char *key) {
    std::shared_lock lock(store().mu);
    auto it = store().data.find(key);
    if (it == store().data.end() || it->second.tag != 0) return nullptr;
    return dup_cstr(it->second.str);
}

// ----------------------------------------------------------------- hashes
void sb_hset(const char *key, const char *field, const char *val) {
    std::unique_lock lock(store().mu);
    Value &v = store().data[key];
    if (v.tag != 2) {
        v = Value{};
        v.tag = 2;
    }
    v.hash[field] = val;
}

char *sb_hget(const char *key, const char *field) {
    std::shared_lock lock(store().mu);
    auto it = store().data.find(key);
    if (it == store().data.end() || it->second.tag != 2) return nullptr;
    auto f = it->second.hash.find(field);
    if (f == it->second.hash.end()) return nullptr;
    return dup_cstr(f->second);
}

// Framed "<n>\n<len> field\n<len> value\n..." dump of the hash; NULL if the
// key is absent or not a hash.
char *sb_hgetall(const char *key) {
    std::shared_lock lock(store().mu);
    auto it = store().data.find(key);
    if (it == store().data.end() || it->second.tag != 2) return nullptr;
    std::vector<std::pair<std::string, std::string>> kvs(
        it->second.hash.begin(), it->second.hash.end());
    return dup_cstr(frame(kvs, true));
}

// ------------------------------------------------------------------ lists
void sb_rpush(const char *key, const char *val) {
    std::unique_lock lock(store().mu);
    Value &v = store().data[key];
    if (v.tag != 1) {
        v = Value{};
        v.tag = 1;
    }
    v.list.emplace_back(val);
}

// Batched push: all values land under ONE lock acquisition, so a concurrent
// lrange/llen never observes a partially-applied multi-value RPUSH — Redis's
// atomicity contract for variadic RPUSH.
void sb_rpush_n(const char *key, const char *const *vals, int64_t n) {
    std::unique_lock lock(store().mu);
    Value &v = store().data[key];
    if (v.tag != 1) {
        v = Value{};
        v.tag = 1;
    }
    for (int64_t i = 0; i < n; ++i) {
        v.list.emplace_back(vals[i]);
    }
}

int64_t sb_llen(const char *key) {
    std::shared_lock lock(store().mu);
    auto it = store().data.find(key);
    if (it == store().data.end() || it->second.tag != 1) return 0;
    return static_cast<int64_t>(it->second.list.size());
}

// lrange with Redis's inclusive, negative-index semantics.  Framed
// "<n>\n<len> item\n..."; NULL if absent or not a list.
char *sb_lrange(const char *key, int64_t start, int64_t stop) {
    std::shared_lock lock(store().mu);
    auto it = store().data.find(key);
    if (it == store().data.end() || it->second.tag != 1) return nullptr;
    const auto &lst = it->second.list;
    int64_t n = static_cast<int64_t>(lst.size());
    if (start < 0) start += n;
    if (stop < 0) stop += n;
    if (start < 0) start = 0;
    if (stop >= n) stop = n - 1;
    std::vector<std::pair<std::string, std::string>> kvs;
    for (int64_t i = start; i <= stop && i < n; ++i) {
        kvs.emplace_back(lst[static_cast<size_t>(i)], std::string());
    }
    return dup_cstr(frame(kvs, false));
}

}  // extern "C"
