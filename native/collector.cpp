// collector — native per-home time-series accumulation + streaming
// results.json writer.
//
// The reference's collect path reads every home's Redis hash and appends
// Python floats list-by-list each timestep (dragg/aggregator.py:728-755),
// then re-serializes the whole collected_data dict to JSON every checkpoint
// interval (dragg/aggregator.py:831-844).  At 10k–100k homes both become
// host bottlenecks.  Here chunked device outputs land as one memcpy-like
// append per (series, chunk), and the JSON writer streams number formatting
// with C++17 std::to_chars (shortest round-trip, Python-json compatible).
//
// The writer takes a length-prefixed "plan" composed by Python — raw JSON
// fragments (object keys, static fields, the Summary block) interleaved
// with series references — so all schema knowledge stays in Python and the
// native side only does the hot work: buffering doubles and printing them.
//
// Plan format (bytes):
//   'R' ' ' <len> '\n' <len raw bytes>            — write bytes verbatim
//   'S' ' ' <len> ' ' <home_idx> '\n' <len key bytes>
//                                                  — write JSON array of
//                                                    series[key][home_idx]
// Records repeat until the plan ends.

#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct Collector {
    // series[key] is a column store: per home, a growing vector<double>.
    std::map<std::string, std::vector<std::vector<double>>> series;
    int64_t n_homes = 0;
};

std::mutex g_mu;
std::map<int64_t, Collector *> g_cols;
int64_t g_next = 1;

// Callers must hold g_mu for the duration of any use of the returned
// pointer: every exported col_* function takes the coarse lock for its whole
// body, which makes col_free racing another col_* call safe (the collector
// workload is one writer; fine-grained locking would buy nothing).
Collector *get_locked(int64_t h) {
    auto it = g_cols.find(h);
    return it == g_cols.end() ? nullptr : it->second;
}

void write_double(std::string &out, double v) {
    // Non-finite values use Python json's literals (NaN/Infinity), which
    // json.load round-trips; std::to_chars would emit "nan"/"inf", which it
    // rejects.
    if (std::isnan(v)) {
        out.append("NaN");
        return;
    }
    if (std::isinf(v)) {
        out.append(v < 0 ? "-Infinity" : "Infinity");
        return;
    }
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
    char buf[32];
    auto res = std::to_chars(buf, buf + sizeof buf, v);
    out.append(buf, res.ptr);
#else
    // libstdc++ < 11 (e.g. GCC 10 build images) has no floating-point
    // to_chars; %.17g round-trips every double, at slightly longer output.
    char buf[32];
    int n = std::snprintf(buf, sizeof buf, "%.17g", v);
    out.append(buf, n > 0 ? static_cast<size_t>(n) : 0);
#endif
}

}  // namespace

extern "C" {

int64_t col_new(int64_t n_homes) {
    auto *c = new Collector();
    c->n_homes = n_homes;
    std::lock_guard<std::mutex> lock(g_mu);
    int64_t h = g_next++;
    g_cols[h] = c;
    return h;
}

void col_free(int64_t h) {
    std::lock_guard<std::mutex> lock(g_mu);
    auto it = g_cols.find(h);
    if (it != g_cols.end()) {
        delete it->second;
        g_cols.erase(it);
    }
}

// Append a (n_steps, n_homes) row-major chunk to series `key`.
int col_add_chunk(int64_t h, const char *key, const double *data,
                  int64_t n_steps, int64_t n_homes) {
    std::lock_guard<std::mutex> lock(g_mu);
    Collector *c = get_locked(h);
    if (c == nullptr || n_homes != c->n_homes) return -1;
    auto &cols = c->series[key];
    if (cols.empty()) cols.resize(static_cast<size_t>(n_homes));
    for (int64_t i = 0; i < n_homes; ++i) {
        auto &v = cols[static_cast<size_t>(i)];
        size_t old = v.size();
        v.resize(old + static_cast<size_t>(n_steps));
        for (int64_t t = 0; t < n_steps; ++t) {
            v[old + static_cast<size_t>(t)] = data[t * n_homes + i];
        }
    }
    return 0;
}

// Replace series[key][home_idx] wholesale (checkpoint import).
int col_import_series(int64_t h, const char *key, int64_t home_idx,
                      const double *data, int64_t n) {
    std::lock_guard<std::mutex> lock(g_mu);
    Collector *c = get_locked(h);
    if (c == nullptr || home_idx < 0 || home_idx >= c->n_homes) return -1;
    auto &cols = c->series[key];
    if (cols.empty()) cols.resize(static_cast<size_t>(c->n_homes));
    auto &v = cols[static_cast<size_t>(home_idx)];
    v.assign(data, data + n);
    return 0;
}

int64_t col_series_len(int64_t h, const char *key, int64_t home_idx) {
    std::lock_guard<std::mutex> lock(g_mu);
    Collector *c = get_locked(h);
    if (c == nullptr) return -1;
    auto it = c->series.find(key);
    if (it == c->series.end() || home_idx < 0 ||
        home_idx >= static_cast<int64_t>(it->second.size())) {
        return 0;
    }
    return static_cast<int64_t>(it->second[static_cast<size_t>(home_idx)].size());
}

// Copy series[key][home_idx] into out (caller-allocated, cap doubles).
int64_t col_get_series(int64_t h, const char *key, int64_t home_idx,
                       double *out, int64_t cap) {
    std::lock_guard<std::mutex> lock(g_mu);
    Collector *c = get_locked(h);
    if (c == nullptr) return -1;
    auto it = c->series.find(key);
    if (it == c->series.end() || home_idx < 0 ||
        home_idx >= static_cast<int64_t>(it->second.size())) {
        return 0;
    }
    const auto &v = it->second[static_cast<size_t>(home_idx)];
    int64_t n = static_cast<int64_t>(v.size());
    if (n > cap) n = cap;
    std::memcpy(out, v.data(), static_cast<size_t>(n) * sizeof(double));
    return n;
}

// Execute a write plan (see header comment).  Returns 0 on success.
int col_write_json(int64_t h, const char *path, const char *plan,
                   int64_t plan_len) {
    std::lock_guard<std::mutex> lock(g_mu);
    Collector *c = get_locked(h);
    if (c == nullptr) return -1;
    std::string tmp_path = std::string(path) + ".tmp";
    std::FILE *f = std::fopen(tmp_path.c_str(), "wb");
    if (f == nullptr) return -2;

    std::string buf;
    buf.reserve(1 << 20);
    const char *p = plan;
    const char *end = plan + plan_len;
    int rc = 0;
    while (p < end && rc == 0) {
        char kind = *p;
        p += 2;  // skip kind + space
        char *after = nullptr;
        long long len = std::strtoll(p, &after, 10);
        p = after;
        long long home_idx = -1;
        if (kind == 'S') {
            home_idx = std::strtoll(p, &after, 10);
            p = after;
        }
        if (p >= end || *p != '\n') { rc = -3; break; }
        ++p;
        if (p + len > end) { rc = -3; break; }
        if (kind == 'R') {
            buf.append(p, static_cast<size_t>(len));
        } else if (kind == 'S') {
            std::string key(p, static_cast<size_t>(len));
            auto it = c->series.find(key);
            buf.push_back('[');
            if (it != c->series.end() && home_idx >= 0 &&
                home_idx < static_cast<int64_t>(it->second.size())) {
                const auto &v = it->second[static_cast<size_t>(home_idx)];
                for (size_t i = 0; i < v.size(); ++i) {
                    if (i != 0) buf.append(", ");
                    write_double(buf, v[i]);
                }
            }
            buf.push_back(']');
        } else {
            rc = -3;
            break;
        }
        p += len;
        if (buf.size() > (1 << 20)) {
            if (std::fwrite(buf.data(), 1, buf.size(), f) != buf.size()) rc = -5;
            buf.clear();
        }
    }
    if (rc == 0 && !buf.empty()) {
        if (std::fwrite(buf.data(), 1, buf.size(), f) != buf.size()) rc = -5;
    }
    // A short write or close failure (e.g. ENOSPC) must NOT rename a
    // truncated file into place — the checkpoint atomicity contract depends
    // on it.
    if (std::fclose(f) != 0 && rc == 0) rc = -5;
    if (rc == 0) {
        if (std::rename(tmp_path.c_str(), path) != 0) rc = -4;
    }
    if (rc != 0) {
        std::remove(tmp_path.c_str());
    }
    return rc;
}

}  // extern "C"
