# dragg_tpu container — replaces the reference's python:3 + redis + mongo
# stack (dragg/Dockerfile:1-12, docker-compose.yml:2-29) with a single
# self-contained image: the state bus is in-process (native/statebus.cpp),
# so there are no sidecar services to wait for.
#
#   docker build -t dragg-tpu .
#   docker run --rm -v $PWD/outputs:/app/outputs dragg-tpu \
#       python -m dragg_tpu run --outputs-dir outputs
#
# For TPU VMs, base on a TPU-enabled JAX image instead:
#   docker build --build-arg BASE=us-docker.pkg.dev/ml-images/public/jax-tpu:latest -t dragg-tpu .
ARG BASE=python:3.12-slim
FROM ${BASE}

WORKDIR /app

# Native toolchain for the C++ statebus/collector extension.
RUN apt-get update && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*

COPY pyproject.toml ./
COPY dragg_tpu ./dragg_tpu
COPY native ./native
COPY bench.py ./

# CPU JAX by default; the TPU base image ships its own jax[tpu].
RUN python -c "import jax" 2>/dev/null || pip install --no-cache-dir jax flax
RUN pip install --no-cache-dir numpy pandas matplotlib && \
    pip install --no-cache-dir -e . --no-deps

# Environment knobs mirror the reference's (DATA_DIR/CONFIG_FILE/OUTPUT_DIR,
# dragg/aggregator.py:31-37; REDIS_HOST is gone — no Redis).
ENV OUTPUT_DIR=/app/outputs

CMD ["python", "-m", "dragg_tpu", "run", "--outputs-dir", "/app/outputs"]
